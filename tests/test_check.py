"""Tests for the check/ subsystem: every bundled rule (positive AND
negative), the engine machinery (noqa, exemption, JSON, baseline), the
`pifft check` CLI, and the runtime guards (recompile budget, tracer
leak) — including the seeded retrace regression the guard must catch.

The capstone is test_package_matches_committed_baseline: the analyzer
over the real package + bench.py must produce no findings beyond the
committed baseline, so any new violation fails tier-1 CI.
"""

import json
import os
import textwrap

import pytest

from cs87project_msolano2_tpu import check
from cs87project_msolano2_tpu.check import engine
from cs87project_msolano2_tpu.check.cli import main as check_cli_main
from cs87project_msolano2_tpu.check.runtime import (
    RecompileBudgetExceeded,
    RecompileGuard,
    tracer_leak_guard,
)

PKG_DIR = os.path.dirname(os.path.abspath(check.__file__))
PKG = os.path.dirname(PKG_DIR)
REPO = os.path.dirname(PKG)


def run(code, rule=None, path="snippet.py"):
    return check.check_source(
        path, textwrap.dedent(code), rules=[rule] if rule else None)


def rule_ids(findings):
    return [f.rule for f in findings]


# --------------------------------------------------------------- registry


def test_at_least_eight_rules_registered():
    rules = check.all_rules()
    assert len(rules) >= 8
    for rid, r in rules.items():
        assert rid == r.id and r.name and r.summary and r.invariant


# ---------------------------------------------------- PIF101 host sync


SYNC_WINDOW = """
    import time
    import numpy as np

    def measure(fn, x):
        t0 = time.perf_counter()
        y = {stmt}
        return (time.perf_counter() - t0) * 1e3, y
"""


@pytest.mark.parametrize("stmt", [
    "np.asarray(fn(x))",
    "float(fn(x))",
    "fn(x).item()",
    "fn(x).block_until_ready()",
    "time.sleep(0.1)",
])
def test_pif101_flags_host_sync_in_window(stmt):
    found = run(SYNC_WINDOW.format(stmt=stmt), "PIF101")
    assert rule_ids(found) == ["PIF101"]


@pytest.mark.parametrize("stmt", [
    "fn(x)",          # no sync at all
    "float(1.5)",     # constant: no device fetch
])
def test_pif101_clean_window(stmt):
    assert run(SYNC_WINDOW.format(stmt=stmt), "PIF101") == []


def test_pif101_sync_riding_the_close_statement():
    """A host fetch embedded in the stop expression itself still
    executes inside the window — the closing statement is scanned."""
    code = """
        import time

        def measure(fn, x, scale):
            t0 = time.perf_counter()
            y = fn(x)
            return (time.perf_counter() - t0) * scale.item(), y
    """
    found = run(code, "PIF101")
    assert rule_ids(found) == ["PIF101"]
    assert ".item()" in found[0].message


def test_pif101_sync_outside_window_is_fine():
    code = """
        import time
        import numpy as np

        def measure(fn, x):
            t0 = time.perf_counter()
            y = fn(x)
            ms = (time.perf_counter() - t0) * 1e3
            return ms, np.asarray(y)
    """
    assert run(code, "PIF101") == []


def test_pif101_timing_layer_exempt():
    code = SYNC_WINDOW.format(stmt="float(fn(x))")
    assert run(code, "PIF101", path="pkg/utils/timing.py") == []


# ------------------------------------------------- PIF102 wall clock


def test_pif102_flags_direct_wall_clock():
    code = """
        import time

        def now_ms():
            return time.time() * 1e3
    """
    assert rule_ids(run(code, "PIF102")) == ["PIF102"]


def test_pif102_sees_through_from_import_alias():
    code = """
        from time import perf_counter as pc

        def now():
            return pc()
    """
    assert rule_ids(run(code, "PIF102")) == ["PIF102"]


def test_pif102_timing_layer_exempt():
    code = "import time\nt = time.perf_counter()\n"
    assert run(code, "PIF102", path="x/utils/timing.py") == []
    assert rule_ids(run(code, "PIF102")) == ["PIF102"]


# ------------------------------------------- PIF103 block_until_ready


def test_pif103_flags_raw_barrier():
    code = """
        import jax

        def wait(y):
            return jax.block_until_ready(y)
    """
    assert rule_ids(run(code, "PIF103")) == ["PIF103"]


def test_pif103_flags_method_form():
    code = "def wait(y):\n    return y.block_until_ready()\n"
    assert rule_ids(run(code, "PIF103")) == ["PIF103"]


def test_pif103_timing_block_helper_is_fine():
    code = """
        from cs87project_msolano2_tpu.utils.timing import block

        def wait(y):
            return block(y)
    """
    assert run(code, "PIF103") == []


# ------------------------------------------ PIF104 multi pallas trips


def test_pif104_flags_two_direct_pallas_calls():
    code = """
        from jax.experimental import pallas as pl

        def fft_pallas_chain(x):
            y = pl.pallas_call(k1, grid=(4,))(x)
            return pl.pallas_call(k2, grid=(4,))(y)
    """
    found = run(code, "PIF104")
    assert rule_ids(found) == ["PIF104"]
    assert "fft_pallas_chain" in found[0].message


def test_pif104_resolves_local_wrappers_by_fixpoint():
    # neither helper is named *_pallas*; the entry point reaches two
    # round trips only THROUGH them — the fixpoint must still see it
    code = """
        from jax.experimental import pallas as pl

        def stage_a(x):
            return pl.pallas_call(k1, grid=(1,))(x)

        def stage_b(x):
            return stage_a(x)

        def whole_pallas_path(x):
            y = stage_a(x)
            return stage_b(y)
    """
    found = run(code, "PIF104")
    assert rule_ids(found) == ["PIF104"]
    assert "whole_pallas_path" in found[0].message


def test_pif104_counts_trips_through_a_single_wrapper_call():
    # ONE call site reaching TWO round trips through a local helper
    # must still flag: the fixpoint carries trip counts, not just
    # reachability
    code = """
        from jax.experimental import pallas as pl

        def helper(x):
            y = pl.pallas_call(k1, grid=(1,))(x)
            return pl.pallas_call(k2, grid=(1,))(y)

        def whole_pallas(x):
            return helper(x)
    """
    found = run(code, "PIF104")
    assert [f.rule for f in found].count("PIF104") >= 1
    assert any("whole_pallas" in f.message and "2 trips" in f.message
               for f in found)


def test_pif104_nested_launcher_counts_once():
    # one round trip through a nested closure: the pallas_call belongs
    # to `launch`, and fft_rows_pallas reaches it once — descending
    # into the nested def AND weighting its call site would
    # double-count and falsely flag
    code = """
        from jax.experimental import pallas as pl

        def fft_rows_pallas(x):
            def launch(y):
                return pl.pallas_call(k1, grid=(4,))(y)
            return launch(x)
    """
    assert run(code, "PIF104") == []


def test_pif104_same_named_defs_do_not_collide():
    # another function's nested two-trip closure named `helper` must
    # not poison resolution of the module-level single-trip `helper`:
    # bare-name calls resolve to own nested defs, then module scope
    code = """
        from jax.experimental import pallas as pl

        def other(x):
            def helper(y):
                a = pl.pallas_call(k1, grid=(1,))(y)
                return pl.pallas_call(k2, grid=(1,))(a)
            return helper(x)

        def helper(y):
            return pl.pallas_call(k1, grid=(1,))(y)

        def fft_rows_pallas(x):
            return helper(x)
    """
    assert run(code, "PIF104") == []


def test_pif104_sibling_nested_helpers_resolve():
    # trips routed nested-helper -> sibling nested helper must still
    # count: resolution walks the lexical chain, not just own children
    code = """
        from jax.experimental import pallas as pl

        def whole_pallas(x):
            def a(y):
                return pl.pallas_call(k1, grid=(1,))(y)
            def b(y):
                return a(a(y))
            return b(x)
    """
    found = run(code, "PIF104")
    assert [f.rule for f in found].count("PIF104") >= 1
    assert any("whole_pallas" in f.message for f in found)


def test_pif104_single_trip_and_unmatched_names_pass():
    code = """
        from jax.experimental import pallas as pl

        def fft_rows_pallas(x):
            return pl.pallas_call(k1, grid=(4,))(x)

        def two_kernel_driver(x):  # not *_pallas*: out of scope
            y = fft_rows_pallas(x)
            return fft_rows_pallas(y)
    """
    assert run(code, "PIF104") == []


def test_pif104_kernel_module_is_clean():
    """The shipped kernel module must satisfy PIF104 as committed: the
    single-pass entry points (fused, fourstep, and the hierarchical
    sixstep — one pallas_call each, nested DMA helpers and all) pass
    with NO suppression, and only the documented two-trip fallbacks
    carry a reasoned noqa (check-baseline.json stays empty)."""
    import re

    kernel_py = os.path.join(PKG, "ops", "pallas_fft.py")
    findings = [f for f in engine.check_paths([kernel_py],
                                              rules=["PIF104"])]
    assert findings == [], [f"{f.path}:{f.line}" for f in findings]
    src = open(kernel_py).read()
    # the single-pass family is clean on its own merits, not via noqa:
    # no PIF104 suppression may appear inside these function bodies
    for entry in ("fft_pi_layout_pallas_sixstep",):
        body = src.split(f"def {entry}")[1].split("\ndef ")[0]
        assert "noqa[PIF104]" not in body, entry
        assert len(re.findall(r"pl\.pallas_call", body)) == 1, entry


def test_pif104_noqa_with_justification():
    code = """
        from jax.experimental import pallas as pl

        def fft_pallas_fallback(x):
            y = pl.pallas_call(k1, grid=(4,))(x)
            return pl.pallas_call(k2, grid=(4,))(y)  # pifft: noqa[PIF104] (deliberate two-trip fallback)
    """
    assert run(code, "PIF104") == []


# ---------------------------------- PIF105 broad except around kernel


def test_pif105_flags_broad_except_around_timed_call():
    code = """
        from cs87project_msolano2_tpu.utils.timing import loop_slope_ms

        def measure(body, args):
            try:
                return loop_slope_ms(body, args)
            except Exception as e:
                print(e)
                return None
    """
    found = run(code, "PIF105")
    assert rule_ids(found) == ["PIF105"]
    assert "classify" in found[0].message


def test_pif105_flags_bare_except_around_pallas_call():
    code = """
        from jax.experimental import pallas as pl

        def launch(k, s, x):
            try:
                return pl.pallas_call(k, out_shape=s)(x)
            except:
                return None
    """
    found = run(code, "PIF105")
    assert rule_ids(found) == ["PIF105"]


def test_pif105_classifying_handler_is_fine():
    code = """
        from cs87project_msolano2_tpu.resilience import classify
        from cs87project_msolano2_tpu.utils.timing import loop_slope_ms

        def measure(body, args, warn):
            try:
                return loop_slope_ms(body, args)
            except Exception as e:
                warn(f"failed ({classify(e).value})")
                return None
    """
    assert run(code, "PIF105") == []


def test_pif105_with_retry_handler_is_fine():
    code = """
        from cs87project_msolano2_tpu.resilience import call_with_retry
        from cs87project_msolano2_tpu.utils.timing import time_ms

        def measure(body, args):
            try:
                return time_ms(body, args)
            except Exception as e:
                return call_with_retry(body, args)
    """
    assert run(code, "PIF105") == []


def test_pif105_narrow_type_and_unrelated_try_pass():
    code = """
        from cs87project_msolano2_tpu.utils.timing import loop_slope_ms

        def measure(body, args):
            try:
                return loop_slope_ms(body, args)
            except ValueError:
                return None

        def other(fn):
            try:
                return fn()
            except Exception as e:
                print(e)
    """
    assert run(code, "PIF105") == []


def test_pif105_resilience_and_timing_layers_exempt():
    code = """
        from cs87project_msolano2_tpu.utils.timing import time_ms

        def probe(fn, args):
            try:
                return time_ms(fn, args)
            except Exception as e:
                print(e)
    """
    import textwrap as tw

    for exempt_path in (
            os.path.join(PKG, "resilience", "snippet.py"),
            os.path.join(PKG, "utils", "timing.py")):
        assert check.check_source(exempt_path, tw.dedent(code),
                                  rules=["PIF105"]) == []


def test_pif105_noqa_escape():
    code = """
        from cs87project_msolano2_tpu.utils.timing import loop_slope_ms

        def measure(body, args):
            try:
                return loop_slope_ms(body, args)
            except Exception as e:  # pifft: noqa[PIF105] (prototype script)
                print(e)
    """
    assert run(code, "PIF105") == []


# -------------------------------- PIF106 measurement-clock references


def test_pif106_flags_calls_and_bare_references():
    code = """
        import time
        from time import perf_counter as pc

        def f():
            t = time.monotonic()
            timer = pc          # a bare reference dodges call rules
            return t, timer
    """
    findings = run(code, "PIF106")
    # the call's attribute AND the aliased bare reference both flag
    assert rule_ids(findings) == ["PIF106", "PIF106"]
    assert any("time.monotonic" in f.message for f in findings)
    assert any("time.perf_counter" in f.message for f in findings)


def test_pif106_flags_ns_clocks_pif102_misses():
    findings = run("""
        import time

        def f():
            return time.monotonic_ns()
    """, "PIF106")
    assert rule_ids(findings) == ["PIF106"]


def test_pif106_sanctioned_clock_layers_exempt():
    code = """
        import time

        def stamp():
            return time.perf_counter()
    """
    import textwrap as tw

    for exempt_path in (
            os.path.join(PKG, "utils", "timing.py"),
            os.path.join(PKG, "obs", "spans.py")):
        assert check.check_source(exempt_path, tw.dedent(code),
                                  rules=["PIF106"]) == []


def test_pif106_unrelated_time_usage_passes():
    code = """
        import time

        def nap():
            time.sleep(0.1)
            return time.strftime("%H:%M")
    """
    assert run(code, "PIF106") == []


def test_pif106_noqa_escape():
    code = """
        import time

        def wall_ms():
            return time.perf_counter() * 1e3  # pifft: noqa[PIF106]
    """
    assert run(code, "PIF106") == []


# ----------------------------- PIF107 blocking call in async serve path


SERVE_PATH = os.path.join(PKG, "serve", "snippet.py")


def test_pif107_flags_sleep_and_open_in_async_serve_code():
    code = """
        import time

        async def worker(q):
            time.sleep(0.01)
            with open("shapes.jsonl") as fh:
                return fh.read()
    """
    findings = run(code, "PIF107", path=SERVE_PATH)
    assert rule_ids(findings) == ["PIF107", "PIF107"]
    assert any("time.sleep" in f.message for f in findings)
    assert any("`open`" in f.message for f in findings)


def test_pif107_import_alias_and_socket_methods_flag():
    code = """
        from time import sleep as snooze

        async def pump(sock):
            snooze(1)
            return sock.recv(4096)
    """
    findings = run(code, "PIF107", path=SERVE_PATH)
    assert rule_ids(findings) == ["PIF107", "PIF107"]
    assert any(".recv()" in f.message for f in findings)


def test_pif107_outside_serve_and_sync_code_pass():
    code = """
        import time

        async def worker(q):
            time.sleep(0.01)
    """
    # the same async blocking call OUTSIDE serve/ is not this rule's
    # business (PIF101/102 own the general timing discipline)
    assert run(code, "PIF107", path="snippet.py") == []
    # the include glob is anchored on a path SEGMENT: a checkout whose
    # directory merely ends in "serve" must not drag its tree in
    assert run(code, "PIF107",
               path="/home/ci/fft-serve/pkg/mod.py") == []
    # sync startup code in serve/ may do file I/O (shape-set loading)
    sync = """
        def load(path):
            with open(path) as fh:
                return fh.read()
    """
    assert run(sync, "PIF107", path=SERVE_PATH) == []


def test_pif107_asyncio_waits_are_sanctioned():
    code = """
        import asyncio

        async def _wait_for_request(q, timeout_s):
            try:
                return await asyncio.wait_for(q.get(), timeout=timeout_s)
            except asyncio.TimeoutError:
                return None

        async def pace():
            await asyncio.sleep(0.01)
    """
    assert run(code, "PIF107", path=SERVE_PATH) == []


def test_pif107_nested_sync_def_is_executor_territory():
    code = """
        import time

        async def run_batch(loop, planes):
            def staged():
                time.sleep(0.001)  # runs in the executor thread
                return planes
            return await loop.run_in_executor(None, staged)
    """
    assert run(code, "PIF107", path=SERVE_PATH) == []


def test_pif107_noqa_escape():
    code = """
        import time

        async def worker():
            time.sleep(0.01)  # pifft: noqa[PIF107]
    """
    assert run(code, "PIF107", path=SERVE_PATH) == []


def test_pif107_mesh_and_router_paths_in_scope():
    """The mesh routing path is explicitly include-scoped: a blocking
    call in serve/mesh.py or serve/router.py stalls EVERY device's
    queue at once, so those files must stay covered (and are also
    named in the config so a narrowed package glob cannot silently
    drop them)."""
    from cs87project_msolano2_tpu.check.rules import (
        BlockingCallInAsyncServePath,
    )

    paths = BlockingCallInAsyncServePath.default_config["paths"]
    assert "*/serve/mesh.py" in paths and "*/serve/router.py" in paths
    code = """
        import time

        async def _reroute(requests):
            time.sleep(0.01)
    """
    for fname in ("mesh.py", "router.py"):
        findings = run(code, "PIF107",
                       path=os.path.join(PKG, "serve", fname))
        assert rule_ids(findings) == ["PIF107"], fname


def test_pif107_serve_package_is_clean():
    """The shipped serve/ package must satisfy its own rule with no
    suppressions needed (the check-baseline stays empty)."""
    serve_dir = os.path.join(PKG, "serve")
    findings = [f for f in engine.check_paths([serve_dir],
                                              rules=["PIF107"])]
    assert findings == [], [f"{f.path}:{f.line}" for f in findings]


# ---------------------------------------- PIF108 bare collective call


PARALLEL_PATH = os.path.join(PKG, "parallel", "snippet.py")
COLLECTIVES_PATH = os.path.join(PKG, "parallel", "collectives.py")

BARE_A2A = """
    import jax

    def transpose(v, axis):
        return jax.lax.all_to_all(v, axis, split_axis=1,
                                  concat_axis=0, tiled=True)
"""


def test_pif108_flags_bare_collective_in_parallel():
    findings = run(BARE_A2A, "PIF108", path=PARALLEL_PATH)
    assert rule_ids(findings) == ["PIF108"]
    assert "parallel.collectives" in findings[0].message
    # import-alias form resolves through the import map too
    aliased = """
        from jax.lax import psum as reduce_sum

        def total(v, axis):
            return reduce_sum(v, axis)
    """
    findings = run(aliased, "PIF108", path=PARALLEL_PATH)
    assert rule_ids(findings) == ["PIF108"]


def test_pif108_sanctioned_funnel_and_outside_parallel_pass():
    # the funnel module itself is the one sanctioned call site
    assert run(BARE_A2A, "PIF108", path=COLLECTIVES_PATH) == []
    # the same call outside parallel/ is not this rule's business
    assert run(BARE_A2A, "PIF108", path="snippet.py") == []
    # a non-collective jax.lax call in parallel/ passes
    local = """
        import jax

        def slice0(v, i, k):
            return jax.lax.dynamic_slice_in_dim(v, i, k, axis=0)
    """
    assert run(local, "PIF108", path=PARALLEL_PATH) == []


def test_pif108_noqa_suppresses():
    code = """
        import jax

        def transpose(v, axis):
            return jax.lax.all_to_all(  # pifft: noqa[PIF108]
                v, axis, split_axis=1, concat_axis=0, tiled=True)
    """
    assert run(code, "PIF108", path=PARALLEL_PATH) == []


def test_pif108_parallel_package_is_clean():
    """The shipped parallel/ package must satisfy its own rule with no
    suppressions: every collective goes through parallel.collectives
    (the supervised funnel, docs/MULTICHIP.md)."""
    parallel_dir = os.path.join(PKG, "parallel")
    findings = [f for f in engine.check_paths([parallel_dir],
                                              rules=["PIF108"])]
    assert findings == [], [f"{f.path}:{f.line}" for f in findings]
    for name in os.listdir(parallel_dir):
        if name.endswith(".py"):
            src = open(os.path.join(parallel_dir, name)).read()
            assert "noqa[PIF108]" not in src, name


# ------------------------------------- PIF109 ad-hoc metric emission


BENCH_PATH = os.path.join(REPO, "bench.py")
HARNESS_PATH = os.path.join(REPO, "harness", "run_experiments.py")
RECORDS_PATH = os.path.join(PKG, "analyze", "records.py")

ADHOC_DUMPS = """
    import json

    def main(record):
        print(json.dumps(record))
"""


def test_pif109_flags_adhoc_dumps_on_metric_surface():
    for path in (BENCH_PATH, HARNESS_PATH,
                 os.path.join(PKG, "analyze", "cli.py")):
        findings = run(ADHOC_DUMPS, "PIF109", path=path)
        assert rule_ids(findings) == ["PIF109"], path
        assert "analyze.records" in findings[0].message
    # import-alias form resolves through the import map too
    aliased = """
        from json import dump as jd

        def save(record, fh):
            jd(record, fh)
    """
    findings = run(aliased, "PIF109", path=BENCH_PATH)
    assert rule_ids(findings) == ["PIF109"]


def test_pif109_sanctioned_helper_and_outside_surface_pass():
    # the schema'd helper module is the one sanctioned call site
    assert run(ADHOC_DUMPS, "PIF109", path=RECORDS_PATH) == []
    # the same call off the metric surface is not this rule's business
    assert run(ADHOC_DUMPS, "PIF109", path="snippet.py") == []
    assert run(ADHOC_DUMPS, "PIF109",
               path=os.path.join(PKG, "serve", "cli.py")) == []
    # json.load (reading committed rounds) is fine on the surface
    reader = """
        import json

        def load(path):
            with open(path) as fh:
                return json.load(fh)
    """
    assert run(reader, "PIF109", path=BENCH_PATH) == []


def test_pif109_noqa_suppresses():
    code = """
        import json

        def main(record):
            print(json.dumps(record))  # pifft: noqa[PIF109]
    """
    assert run(code, "PIF109", path=BENCH_PATH) == []


def test_pif109_metric_surface_is_clean():
    """The shipped metric-emission surface (bench.py, harness/, the
    analyze package) must satisfy its own rule with no suppressions:
    every record goes through analyze.records (docs/ANALYSIS.md)."""
    surface = [BENCH_PATH, os.path.join(REPO, "harness"),
               os.path.join(PKG, "analyze")]
    findings = [f for f in engine.check_paths(surface, rules=["PIF109"])]
    assert findings == [], [f"{f.path}:{f.line}" for f in findings]
    for root in surface:
        files = [root] if root.endswith(".py") else [
            os.path.join(root, nm) for nm in os.listdir(root)
            if nm.endswith(".py")]
        for path in files:
            assert "noqa[PIF109]" not in open(path).read(), path


# --------------------------------------- PIF122 backend-unaware ceiling


NAKED_UTIL = """
    from cs87project_msolano2_tpu.utils.roofline import (
        roofline_utilization,
    )

    def row(n, ms, kind):
        return roofline_utilization(n, ms, kind, 0)
"""


def test_pif122_flags_backendless_utilization_on_surface():
    for path in (BENCH_PATH,
                 os.path.join(PKG, "serve", "mesh.py"),
                 os.path.join(PKG, "fleet", "canary.py")):
        findings = run(NAKED_UTIL, "PIF122", path=path)
        assert rule_ids(findings) == ["PIF122"], path
        assert "backend=" in findings[0].message
    spectral = """
        from cs87project_msolano2_tpu.utils import roofline

        def row(n, ms, kind):
            return roofline.spectral_roofline_utilization(
                "conv", n, ms, kind)
    """
    assert rule_ids(run(spectral, "PIF122", path=BENCH_PATH)) \
        == ["PIF122"]


def test_pif122_backend_kwarg_scope_and_splat_pass():
    passed = """
        from cs87project_msolano2_tpu.utils.roofline import (
            roofline_utilization,
        )

        def row(n, ms, key):
            return roofline_utilization(n, ms, key.device_kind, 0,
                                        backend=key.backend)

        def splat(n, ms, kind, **kw):
            return roofline_utilization(n, ms, kind, 0, **kw)
    """
    assert run(passed, "PIF122", path=BENCH_PATH) == []
    # off the publishing surface (tests, ops) is not this rule's
    # business, and the model module itself is exempt
    assert run(NAKED_UTIL, "PIF122", path="snippet.py") == []
    assert run(NAKED_UTIL, "PIF122",
               path=os.path.join(PKG, "utils", "roofline.py")) == []


def test_pif122_raw_tpu_table_lookup_flagged():
    raw = """
        from cs87project_msolano2_tpu.utils.roofline import (
            hbm_peak_bytes_per_s,
        )

        def ceiling(kind):
            return hbm_peak_bytes_per_s(kind)
    """
    findings = run(raw, "PIF122", path=BENCH_PATH)
    assert rule_ids(findings) == ["PIF122"]
    assert "backend_peak_bytes_per_s" in findings[0].message
    # the per-backend dispatcher is the sanctioned spelling
    dispatched = """
        from cs87project_msolano2_tpu.utils.roofline import (
            backend_peak_bytes_per_s,
        )

        def ceiling(backend, kind):
            return backend_peak_bytes_per_s(backend, kind)
    """
    assert run(dispatched, "PIF122", path=BENCH_PATH) == []


def test_pif122_noqa_requires_a_reason():
    """PIF122 is strict (blanket_suppressible=False): a blanket or
    reasonless noqa cannot vouch for a published figure."""
    reasonless = """
        from cs87project_msolano2_tpu.utils.roofline import (
            roofline_utilization,
        )

        u = roofline_utilization(n, ms, kind, 0)  # pifft: noqa[PIF122]
    """
    assert rule_ids(run(reasonless, "PIF122", path=BENCH_PATH)) \
        == ["PIF122"]
    blanket = """
        from cs87project_msolano2_tpu.utils.roofline import (
            roofline_utilization,
        )

        u = roofline_utilization(n, ms, kind, 0)  # pifft: noqa
    """
    assert rule_ids(run(blanket, "PIF122", path=BENCH_PATH)) \
        == ["PIF122"]
    reasoned = """
        from cs87project_msolano2_tpu.utils.roofline import (
            roofline_utilization,
        )

        u = roofline_utilization(n, ms, kind, 0)  # pifft: noqa[PIF122]: tpu-only diagnostic, never published
    """
    assert run(reasoned, "PIF122", path=BENCH_PATH) == []


def test_pif122_publishing_surface_is_clean():
    """The shipped figure-publishing surface satisfies its own rule
    with ZERO suppressions — the mandated empty baseline: every
    utilization call passes backend= (docs/BACKENDS.md)."""
    surface = [BENCH_PATH,
               os.path.join(PKG, "serve"), os.path.join(PKG, "fleet"),
               os.path.join(PKG, "analyze"), os.path.join(PKG, "apps"),
               os.path.join(PKG, "hw")]
    findings = list(engine.check_paths(surface, rules=["PIF122"]))
    assert findings == [], [f"{f.path}:{f.line}" for f in findings]
    for root in surface:
        files = [root] if root.endswith(".py") else [
            os.path.join(root, nm) for nm in os.listdir(root)
            if nm.endswith(".py")]
        for path in files:
            assert "noqa[PIF122]" not in open(path).read(), path


# ------------------------------------------- PIF201 nonstatic shape arg


def test_pif201_flags_jit_with_dynamic_shape_param():
    code = """
        import jax

        def fft(x, n):
            return x

        g = jax.jit(fft)
    """
    found = run(code, "PIF201")
    assert rule_ids(found) == ["PIF201"]
    assert "'n'" in found[0].message


def test_pif201_static_argnums_is_fine():
    code = """
        import jax

        def fft(x, n):
            return x

        g = jax.jit(fft, static_argnums=(1,))
        h = jax.jit(fft, static_argnames=("n",))
    """
    assert run(code, "PIF201") == []


def test_pif201_partial_binding_is_fine():
    code = """
        import jax
        from functools import partial

        def fft(x, n):
            return x

        g = jax.jit(partial(fft, n=8))
        h = jax.jit(lambda x: fft(x, 8))
    """
    assert run(code, "PIF201") == []


def test_pif201_flags_pallas_call_kernel_with_shape_param():
    code = """
        from jax.experimental import pallas as pl

        def kernel(tile, x_ref, o_ref):
            o_ref[...] = x_ref[...]

        out = pl.pallas_call(kernel, grid=(4,))
    """
    found = run(code, "PIF201")
    assert rule_ids(found) == ["PIF201"]
    assert "partial" in found[0].message


# --------------------------------------------------- PIF202 jit in loop


def test_pif202_flags_jit_constructed_in_loop():
    code = """
        import jax

        def build(fs):
            out = []
            for f in fs:
                out.append(jax.jit(f))
            return out
    """
    assert rule_ids(run(code, "PIF202")) == ["PIF202"]


def test_pif202_hoisted_or_nested_def_is_fine():
    code = """
        import jax

        def build(f, xs):
            g = jax.jit(f)
            for x in xs:
                g(x)

        def factory(fs):
            # the def body only traces when called; not a per-iteration
            # construction site
            makers = []
            for f in fs:
                def make(f=f):
                    return jax.jit(f)
                makers.append(make)
            return makers
    """
    assert run(code, "PIF202") == []


# ------------------------------------------------ PIF301 sublane rule


def test_pif301_flags_bad_literal_sublane():
    code = """
        from jax.experimental import pallas as pl

        spec = pl.BlockSpec((12, 128), lambda i: (i, 0))
    """
    found = run(code, "PIF301")
    assert rule_ids(found) == ["PIF301"]
    assert "12" in found[0].message


def test_pif301_legal_sublane_dims():
    code = """
        from jax.experimental import pallas as pl

        a = pl.BlockSpec((8, 128), lambda i: (i, 0))
        b = pl.BlockSpec((1, 128), lambda i: (i, 0))
        c = pl.BlockSpec((1024, 128), lambda i: (i, 0))
        d = pl.BlockSpec((R - 1, 1, 1), lambda i: (0, 0, 0))
        e = pl.BlockSpec((levels, qb, 128), lambda i: (0, i, 0))
    """
    assert run(code, "PIF301") == []


def test_pif301_block_shape_kwarg_and_3d():
    code = """
        from jax.experimental import pallas as pl

        a = pl.BlockSpec(block_shape=(1, 20, 128), index_map=None)
    """
    found = run(code, "PIF301")
    assert rule_ids(found) == ["PIF301"]


# ------------------------------------------------ PIF401 PlanKey fields


def test_pif401_flags_underspecified_plankey():
    code = """
        from cs87project_msolano2_tpu.plans import PlanKey

        key = PlanKey(device_kind="cpu-interpret", n=8)
    """
    found = run(code, "PIF401")
    assert rule_ids(found) == ["PIF401"]
    assert "layout" in found[0].message


def test_pif401_fully_specified_and_kwargs_splat():
    code = """
        from cs87project_msolano2_tpu.plans import PlanKey

        a = PlanKey(device_kind="cpu-interpret", n=8, batch=(), \
layout="pi", dtype="float32", precision="split3", domain="c2c", \
backend="cpu-interpret")
        b = PlanKey(**base)  # not statically analyzable: skipped
    """
    assert run(code, "PIF401") == []


def test_pif401_domain_is_compile_relevant():
    """domain joined the covered fields with the any-length ladder:
    an r2c and a c2c key at one non-pow2 n dispatch different
    variants, so a defaulted domain aliases cache entries."""
    code = """
        from cs87project_msolano2_tpu.plans import PlanKey

        a = PlanKey(device_kind="cpu-interpret", n=1000, batch=(), \
layout="natural", dtype="float32", precision="split3")
    """
    found = run(code, "PIF401")
    assert rule_ids(found) == ["PIF401"]
    assert "domain" in found[0].message


def test_pif401_core_module_exempt():
    code = "key = PlanKey(n=8)\n"
    assert run(code, "PIF401", path="x/plans/core.py") == []
    assert rule_ids(run(code, "PIF401")) == ["PIF401"]


# ------------------------------------------------ PIF501 broad except


def test_pif501_flags_swallowing_handlers():
    code = """
        def f():
            try:
                g()
            except Exception:
                pass

        def h():
            try:
                g()
            except:
                return None
    """
    assert rule_ids(run(code, "PIF501")) == ["PIF501", "PIF501"]


def test_pif501_reraise_use_or_narrow_is_fine():
    code = """
        def a():
            try:
                g()
            except Exception as e:
                print(f"failed: {e}")

        def b():
            try:
                g()
            except Exception:
                raise

        def c():
            try:
                g()
            except ValueError:
                pass
    """
    assert run(code, "PIF501") == []


# ------------------------------------------------ PIF502 tables kwarg


def test_pif502_flags_tables_kwarg_call_site():
    code = """
        from cs87project_msolano2_tpu.models.fft import fft

        y = fft(x, 4, tables=t)
    """
    assert rule_ids(run(code, "PIF502")) == ["PIF502"]


def test_pif502_positional_and_def_sites_fine():
    code = """
        def fft(x, p=1, tables=None):
            return x

        y = fft(x, 4, t)
    """
    assert run(code, "PIF502") == []


# ----------------------------------------------------- engine machinery


def test_noqa_suppresses_named_rule():
    code = """
        def f():
            try:
                g()
            except Exception:  # pifft: noqa[PIF501]
                pass
    """
    assert run(code, "PIF501") == []


def test_noqa_blanket_and_wrong_id():
    base = """
        def f():
            try:
                g()
            except Exception:  {noqa}
                pass
    """
    assert run(base.format(noqa="# pifft: noqa"), "PIF501") == []
    found = run(base.format(noqa="# pifft: noqa[PIF101]"), "PIF501")
    assert rule_ids(found) == ["PIF501"]


def test_syntax_error_yields_pif000():
    found = check.check_source("bad.py", "def f(:\n")
    assert rule_ids(found) == ["PIF000"]


def test_unknown_rule_id_raises():
    with pytest.raises(KeyError):
        check.check_source("x.py", "pass\n", rules=["PIF999"])


def test_nonexistent_path_is_a_finding_not_clean(tmp_path):
    """A typo'd path (CI script, pre-commit entry) must fail loudly
    with PIF000, never report a silently-clean run."""
    for bad in (str(tmp_path / "no_such_dir_or_file"),
                str(tmp_path / "missing.py")):
        found = check.check_paths([bad])
        assert rule_ids(found) == ["PIF000"]
        assert "unreadable" in found[0].message


def test_exempt_globs_match_from_any_cwd(tmp_path, monkeypatch):
    """Exemption keys on the absolute path: checking utils/timing.py
    from inside utils/ must still exempt it from the PIF1xx rules."""
    utils = tmp_path / "utils"
    utils.mkdir()
    timing = utils / "timing.py"
    timing.write_text("import time\nt = time.perf_counter()\n")
    monkeypatch.chdir(utils)
    assert check.check_paths(["timing.py"], rules=["PIF102"]) == []


def test_finding_json_round_trip():
    found = run(SYNC_WINDOW.format(stmt="float(fn(x))"), "PIF101")
    payload = json.loads(engine.to_json(found, ["snippet.py"]))
    assert payload["count"] == 1
    back = [engine.Finding.from_record(r) for r in payload["findings"]]
    assert back == found


def test_compare_baseline_new_and_fixed():
    a = engine.Finding("PIF501", "x.py", 3, 0, "m1")
    b = engine.Finding("PIF501", "x.py", 9, 0, "m2")
    c = engine.Finding("PIF102", "y.py", 1, 0, "m3")
    new, fixed = check.compare_baseline([a, c], [a, b])
    assert new == [c]
    assert fixed == [b]


def test_compare_baseline_tolerates_line_drift():
    """An edit above a grandfathered finding moves it (and may renumber
    a line reference embedded in its message) without creating a new
    violation — the baseline must keep matching it."""
    old = engine.Finding("PIF101", "x.py", 30, 4,
                         "host sync inside the window at line 28")
    moved = engine.Finding("PIF101", "x.py", 45, 4,
                           "host sync inside the window at line 43")
    new, fixed = check.compare_baseline([moved], [old])
    assert new == [] and fixed == []


def test_compare_baseline_counts_duplicate_keys():
    """Line drift is forgiven but a genuine SECOND occurrence of the
    same violation in the same file is still new."""
    known = engine.Finding("PIF501", "x.py", 3, 0, "m")
    dup = engine.Finding("PIF501", "x.py", 40, 0, "m")
    new, fixed = check.compare_baseline([known, dup], [known])
    assert new == [dup]
    assert fixed == []


# ------------------------------------------------------ the capstone


def test_package_matches_committed_baseline():
    """New violations anywhere on the default scan surface — the
    package plus every measurement script (bench.py, bench_configs.py,
    exp_perf.py, harness/) — fail CI."""
    from cs87project_msolano2_tpu.check.cli import _default_paths

    findings = check.check_paths(_default_paths())
    baseline = check.load_baseline(os.path.join(REPO,
                                                "check-baseline.json"))
    new, _fixed = check.compare_baseline(findings, baseline)
    assert not new, "new pifft-check findings:\n" + \
        engine.format_human(new)
    # the committed baseline is currently empty (the package is clean);
    # growing it is allowed — the review of that diff IS the gate
    # (pifft check --write-baseline check-baseline.json) — so only new
    # UNbaselined findings fail here.


# ------------------------------------------------------------- the CLI


def test_cli_clean_run_exit_zero(capsys):
    assert check_cli_main([PKG]) == 0
    assert "clean" in capsys.readouterr().out


def test_cli_findings_exit_one(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    f()\nexcept Exception:\n    pass\n")
    assert check_cli_main([str(bad)]) == 1
    assert "PIF501" in capsys.readouterr().out


def test_cli_rule_filter_and_json(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import time\nt = time.time()\ntry:\n    f()\n"
                   "except Exception:\n    pass\n")
    assert check_cli_main([str(bad), "--rule", "PIF501", "--json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert [f["rule"] for f in payload["findings"]] == ["PIF501"]


def test_cli_list_rules(capsys):
    assert check_cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("PIF101", "PIF201", "PIF301", "PIF401", "PIF501"):
        assert rid in out


def test_cli_baseline_workflow(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    f()\nexcept Exception:\n    pass\n")
    base = tmp_path / "base.json"
    assert check_cli_main([str(bad), "--write-baseline", str(base)]) == 0
    # grandfathered: same findings, baseline makes the run pass
    assert check_cli_main([str(bad), "--baseline", str(base)]) == 0
    # a NEW violation fails even with the baseline
    bad.write_text(bad.read_text() +
                   "\ntry:\n    f()\nexcept Exception:\n    pass\n")
    capsys.readouterr()
    assert check_cli_main([str(bad), "--baseline", str(base)]) == 1
    assert "NEW" in capsys.readouterr().out


def test_cli_malformed_baseline_is_usage_error(tmp_path, capsys):
    """A truncated/hand-edited baseline exits 2 with a message, never
    an uncaught traceback (exit 1 would read as 'new findings')."""
    base = tmp_path / "base.json"
    good = tmp_path / "ok.py"
    good.write_text("x = 1\n")
    for payload in ('{"findings": [{"rule": "PIF501"}]}', "not json",
                    "[]", '{"findings": 3}'):
        base.write_text(payload)
        assert check_cli_main([str(good), "--baseline", str(base)]) == 2
        assert "cannot read baseline" in capsys.readouterr().err


def test_cli_default_paths_work_from_any_cwd(tmp_path, monkeypatch,
                                             capsys):
    """The no-args run resolves the package + bench.py from the repo
    the package was imported from, opens them as real paths, and keys
    output repo-root-relative — all independent of cwd."""
    monkeypatch.chdir(tmp_path)
    assert check_cli_main(["--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["paths"][:2] == ["cs87project_msolano2_tpu",
                                    "bench.py"]
    assert "harness" in payload["paths"]
    assert payload["count"] == 0


def test_cli_via_main_entry(capsys):
    from cs87project_msolano2_tpu.cli import main

    assert main(["check", PKG]) == 0


# ----------------------------------------------------- runtime guards


def test_recompile_guard_stable_shapes_pass():
    import jax.numpy as jnp

    guard = RecompileGuard()
    f = guard.jit(lambda x: x * 2, budget=1, name="double")
    x = jnp.ones(8)
    for _ in range(4):
        f(x)
    guard.verify()
    assert guard.report() == [
        {"name": "double", "budget": 1, "traces": 1}]


def test_recompile_guard_catches_seeded_retrace():
    """The seeded regression: unstable shapes retrace past the budget
    and the guard MUST fail."""
    import jax.numpy as jnp

    guard = RecompileGuard()
    f = guard.jit(lambda x: x * 2, budget=1, name="unstable")
    for n in (4, 8, 16):  # each shape is a fresh trace
        f(jnp.ones(n))
    assert guard.over_budget()[0]["traces"] == 3
    with pytest.raises(RecompileBudgetExceeded, match="unstable"):
        guard.verify()


def test_recompile_guard_budget_allows_known_shape_set():
    import jax.numpy as jnp

    guard = RecompileGuard()
    f = guard.jit(lambda x: x + 1, budget=2)
    f(jnp.ones(4))
    f(jnp.ones(8))
    f(jnp.ones(4))  # cache hit, not a trace
    guard.verify()


def test_recompile_guard_no_spurious_failure_under_disable_jit():
    """In no-jit debug runs the wrapped fn executes every call; the
    guard must not misread call count as trace count."""
    import jax
    import jax.numpy as jnp

    guard = RecompileGuard()
    f = guard.jit(lambda x: x * 2, budget=1)
    with jax.disable_jit():
        for _ in range(4):
            f(jnp.ones(4))
    guard.verify()
    assert guard.report()[0]["traces"] == 0


def test_recompile_guard_fixture_integration(recompile_guard):
    import jax.numpy as jnp

    f = recompile_guard.jit(lambda x: x - 1, budget=1)
    f(jnp.ones(4))
    f(jnp.ones(4))


def test_plan_executor_traces_once(recompile_guard):
    """Real-usage guard: the plan executor is shape-stable — repeated
    same-shape calls must not retrace (a retrace would hide XLA compile
    inside a timed window on the relay)."""
    import jax.numpy as jnp

    from cs87project_msolano2_tpu import plans

    plan = plans.plan(256, layout="pi")
    f = recompile_guard.jit(plan.fn, budget=1, name="plan-executor")
    xr = jnp.ones(256)
    xi = jnp.zeros(256)
    for _ in range(3):
        f(xr, xi)


def test_tracer_leak_guard_catches_leak():
    import jax
    import jax.numpy as jnp

    leaked = []

    def f(x):
        leaked.append(x)  # the classic leak: tracer stored outside
        return x * 2

    with pytest.raises(Exception, match="[Ll]eak"):
        with tracer_leak_guard():
            jax.jit(f)(jnp.ones(4))


def test_tracer_leak_guard_clean_fn(no_tracer_leaks):
    import jax
    import jax.numpy as jnp

    assert float(jax.jit(lambda x: x * 2)(jnp.ones(()))) == 2.0


# ------------------------------------ noqa hygiene (PIF503) + audit


def test_pif503_flags_reasonless_noqa():
    code = """
        def f():
            try:
                g()
            except Exception:  # pifft: noqa[PIF501]
                pass
    """
    found = run(code, "PIF503")
    assert rule_ids(found) == ["PIF503"]
    assert "PIF501" in found[0].message


def test_pif503_reasoned_noqa_is_clean():
    code = """
        def f():
            try:
                g()
            except Exception:  # pifft: noqa[PIF501]: boundary of last resort, logged upstream
                pass
    """
    assert run(code, "PIF503") == []


def test_pif503_not_silenced_by_blanket_noqa():
    code = """
        def f():
            x = 1  # pifft: noqa
    """
    found = run(code, "PIF503")
    assert rule_ids(found) == ["PIF503"]


def test_pif503_reasonless_self_listing_does_not_vouch():
    code = """
        def f():
            x = 1  # pifft: noqa[PIF503]
    """
    assert rule_ids(run(code, "PIF503")) == ["PIF503"]


def test_pif503_reasoned_blanket_is_clean():
    code = """
        def f():
            x = 1  # pifft: noqa: generated table, every rule misfires here
    """
    assert run(code, "PIF503") == []
    # and the reasoned blanket still suppresses ordinary rules
    code2 = """
        def f():
            try:
                g()
            except Exception:  # pifft: noqa: prototype boundary, reviewed
                pass
    """
    assert run(code2, "PIF501") == []


def test_noqa_inside_string_literal_is_not_a_suppression():
    """The scanner tokenizes: a noqa tag inside a string (a rule
    message, a doc example) neither suppresses nor gets audited."""
    code = '''
        MESSAGE = "justify with # pifft: noqa[PIF104]"

        def f():
            try:
                g()
            except Exception:
                pass
    '''
    # the PIF501 on the handler line is NOT suppressed by the string
    found = run(code, "PIF501")
    assert rule_ids(found) == ["PIF501"]
    # and PIF503 does not audit the string either
    assert run(code, "PIF503") == []


def test_collect_noqa_inventory():
    src = textwrap.dedent("""
        a = 1  # pifft: noqa[PIF101]: reasoned
        b = 2  # pifft: noqa
    """)
    ctx_records = []
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "mod.py")
        with open(p, "w", encoding="utf-8") as fh:
            fh.write(src)
        ctx_records = engine.collect_noqa([p])
    assert len(ctx_records) == 2
    reasoned = next(r for r in ctx_records if r["ids"] == ["PIF101"])
    blanket = next(r for r in ctx_records if r["ids"] == ["*"])
    assert reasoned["reason"] == "reasoned"
    assert blanket["reason"] is None


def test_cli_list_noqa(tmp_path, capsys):
    p = tmp_path / "mod.py"
    p.write_text("a = 1  # pifft: noqa[PIF102]: host stamp\n"
                 "b = 2  # pifft: noqa\n")
    assert check_cli_main([str(p), "--list-noqa"]) == 0
    out = capsys.readouterr().out
    assert "host stamp" in out
    assert "NO REASON" in out
    assert "2 suppression(s)" in out


def test_shipped_tree_noqa_all_have_reasons():
    """The in-tree suppression inventory is fully reasoned — the
    PIF503 satellite's acceptance gate."""
    from cs87project_msolano2_tpu.check.cli import _default_paths

    records = engine.collect_noqa(_default_paths())
    missing = [r for r in records if not r["reason"]]
    assert records, "expected at least one audited suppression"
    assert missing == [], missing


# ------------------------------------------------------ SARIF output


def test_sarif_output_validates_schema_shape(tmp_path):
    """`--format sarif` must emit SARIF 2.1.0: version, one run with
    tool.driver.name + rules metadata, results carrying ruleId and
    physical locations with line/column regions."""
    import io as _io
    from contextlib import redirect_stdout

    p = tmp_path / "probe.py"
    p.write_text("import time\n\ndef f():\n"
                 "    t0 = time.perf_counter()\n")
    buf = _io.StringIO()
    with redirect_stdout(buf):
        rc = check_cli_main([str(p), "--rule", "PIF102",
                             "--format", "sarif"])
    assert rc == 1
    doc = json.loads(buf.getvalue())
    assert doc["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in doc["$schema"]
    (run_,) = doc["runs"]
    driver = run_["tool"]["driver"]
    assert driver["name"] == "pifft-check"
    rule_meta = {r["id"]: r for r in driver["rules"]}
    assert "PIF102" in rule_meta
    assert rule_meta["PIF102"]["shortDescription"]["text"]
    (result,) = run_["results"]
    assert result["ruleId"] == "PIF102"
    assert result["level"] == "error"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("probe.py")
    assert loc["region"]["startLine"] == 4
    assert loc["region"]["startColumn"] >= 1


def test_sarif_clean_run_has_empty_results(tmp_path):
    import io as _io
    from contextlib import redirect_stdout

    p = tmp_path / "ok.py"
    p.write_text("x = 1\n")
    buf = _io.StringIO()
    with redirect_stdout(buf):
        rc = check_cli_main([str(p), "--format", "sarif"])
    assert rc == 0
    doc = json.loads(buf.getvalue())
    assert doc["runs"][0]["results"] == []


# ------------------------------------------------- --changed scoping


def _git(repo, *args):
    import subprocess

    proc = subprocess.run(["git", "-C", str(repo), *args],
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    return proc.stdout


@pytest.fixture
def git_repo(tmp_path):
    repo = tmp_path / "r"
    (repo / "pkg").mkdir(parents=True)
    _git(tmp_path, "init", "-q", str(repo))
    _git(repo, "config", "user.email", "t@t")
    _git(repo, "config", "user.name", "t")
    (repo / "pkg" / "a.py").write_text(
        "import time\n\ndef a():\n    t0 = time.perf_counter()\n")
    (repo / "pkg" / "b.py").write_text("b = 1\n")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "init")
    return repo


def test_changed_scopes_to_touched_files(git_repo, capsys):
    """--changed checks ONLY files differing vs the ref: the committed
    PIF102 violation in a.py is invisible until a.py itself changes."""
    # nothing changed -> clean exit, nothing checked
    assert check_cli_main([str(git_repo / "pkg"), "--changed", "HEAD",
                           "--rule", "PIF102"]) == 0
    assert "no files changed" in capsys.readouterr().out
    # touch only the CLEAN file -> still no findings (a.py not scanned)
    (git_repo / "pkg" / "b.py").write_text("b = 2\n")
    assert check_cli_main([str(git_repo / "pkg"), "--changed", "HEAD",
                           "--rule", "PIF102"]) == 0
    capsys.readouterr()
    # an UNTRACKED file with a violation is in scope
    (git_repo / "pkg" / "c.py").write_text(
        "import time\n\ndef c():\n    t0 = time.perf_counter()\n")
    assert check_cli_main([str(git_repo / "pkg"), "--changed", "HEAD",
                           "--rule", "PIF102"]) == 1
    out = capsys.readouterr().out
    assert "c.py" in out and "a.py" not in out
    # committing moves it out of the changed set again
    _git(git_repo, "add", "-A")
    _git(git_repo, "commit", "-qm", "more")
    assert check_cli_main([str(git_repo / "pkg"), "--changed", "HEAD",
                           "--rule", "PIF102"]) == 0


def test_changed_vs_earlier_ref_sees_committed_diff(git_repo, capsys):
    (git_repo / "pkg" / "a.py").write_text(
        "import time\n\ndef a():\n    t0 = time.perf_counter()\n"
        "    t1 = time.perf_counter()\n")
    _git(git_repo, "add", "-A")
    _git(git_repo, "commit", "-qm", "touch a")
    assert check_cli_main([str(git_repo / "pkg"), "--changed", "HEAD~1",
                           "--rule", "PIF102"]) == 1
    assert "a.py" in capsys.readouterr().out


def test_changed_bad_ref_is_usage_error(git_repo, capsys):
    rc = check_cli_main([str(git_repo / "pkg"), "--changed",
                         "no-such-ref", "--rule", "PIF102"])
    assert rc == 2
    assert "--changed" in capsys.readouterr().err


def test_cli_list_noqa_respects_changed_scope(git_repo, capsys):
    (git_repo / "pkg" / "n.py").write_text(
        "a = 1  # pifft: noqa[PIF102]: untracked-file suppression\n")
    # a.py's committed suppressions (none) + only the untracked file
    # is in the changed scope
    assert check_cli_main([str(git_repo / "pkg"), "--changed", "HEAD",
                           "--list-noqa"]) == 0
    out = capsys.readouterr().out
    assert "untracked-file suppression" in out
    assert "1 suppression(s)" in out


def test_cli_list_noqa_sarif_is_usage_error(tmp_path, capsys):
    p = tmp_path / "m.py"
    p.write_text("x = 1\n")
    assert check_cli_main([str(p), "--list-noqa",
                           "--format", "sarif"]) == 2
    assert "--list-noqa" in capsys.readouterr().err


# ----------------------- the interprocedural layer's engine surface:
# summary cache, --changed staleness closure, --stats, SARIF codeFlows

ALLOC_CLEAN = (
    "import numpy as np\n"
    "\n"
    "MAX = 4096\n"
    "\n"
    "def stage(width):\n"
    "    width = min(width, MAX)\n"
    "    return np.zeros(width)\n")

ALLOC_UNCLAMPED = (
    "import numpy as np\n"
    "\n"
    "def stage(width):\n"
    "    return np.zeros(width)\n")

RECV = (
    "from pkg.serve.alloc import stage\n"
    "\n"
    "def on_frame(frame):\n"
    "    return stage(frame.width)\n")


@pytest.fixture
def taint_repo(tmp_path, monkeypatch):
    """A git repo with a serve-layer caller/callee pair (clean as
    committed) and a live summary cache."""
    repo = tmp_path / "r"
    (repo / "pkg" / "serve").mkdir(parents=True)
    _git(tmp_path, "init", "-q", str(repo))
    _git(repo, "config", "user.email", "t@t")
    _git(repo, "config", "user.name", "t")
    (repo / "pkg" / "serve" / "alloc.py").write_text(ALLOC_CLEAN)
    (repo / "pkg" / "serve" / "recv.py").write_text(RECV)
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "init")
    monkeypatch.chdir(repo)
    monkeypatch.setenv("PIFFT_CHECK_CACHE", str(tmp_path / "cache.json"))
    return repo


def test_changed_refires_caller_after_callee_edit(taint_repo, capsys):
    """The edited-callee staleness fix: the caller's interprocedural
    finding depends on the callee's summary, so a --changed run that
    touched ONLY the callee must re-check the caller."""
    # full warm run: clean, and the summary cache is now populated
    assert check_cli_main(["pkg", "--rule", "PIF118"]) == 0
    capsys.readouterr()
    # edit ONLY the callee: drop the clamp
    (taint_repo / "pkg" / "serve" / "alloc.py").write_text(
        ALLOC_UNCLAMPED)
    rc = check_cli_main(["pkg", "--changed", "HEAD",
                         "--rule", "PIF118"])
    captured = capsys.readouterr()
    assert rc == 1
    # the finding anchors at the wire read in the UNCHANGED caller —
    # reachable only because the cache's call edges pulled recv.py
    # back into scope
    assert "recv.py" in captured.out
    assert "1 dependent caller file(s)" in captured.err


def test_changed_without_dependents_stays_narrow(taint_repo, capsys):
    assert check_cli_main(["pkg", "--rule", "PIF118"]) == 0
    capsys.readouterr()
    # a new leaf file calls nothing the others define and nothing
    # calls it: no closure growth
    (taint_repo / "pkg" / "serve" / "extra.py").write_text("x = 1\n")
    assert check_cli_main(["pkg", "--changed", "HEAD",
                           "--rule", "PIF118"]) == 0
    assert "dependent caller" not in capsys.readouterr().err


def test_summary_cache_warm_second_run(tmp_path):
    from cs87project_msolano2_tpu.check import summaries

    d = tmp_path / "serve"
    d.mkdir()
    (d / "alloc.py").write_text(ALLOC_UNCLAMPED)
    cpath = str(tmp_path / "c.json")

    cold = engine.RunStats()
    found1 = check.check_paths([str(d)], rules=["PIF118"], stats=cold,
                               cache=summaries.SummaryCache(cpath))
    assert cold.cache["misses"] == 1 and cold.cache["hits"] == 0
    assert os.path.exists(cpath)

    warm = engine.RunStats()
    found2 = check.check_paths([str(d)], rules=["PIF118"], stats=warm,
                               cache=summaries.SummaryCache(cpath))
    assert warm.cache["misses"] == 0 and warm.cache["hits"] == 1
    # cached summaries reproduce the findings exactly
    assert [f.key() for f in found1] == [f.key() for f in found2]


def test_summary_cache_invalidates_on_content_change(tmp_path):
    from cs87project_msolano2_tpu.check import summaries

    d = tmp_path / "serve"
    d.mkdir()
    p = d / "alloc.py"
    p.write_text(ALLOC_CLEAN)
    cpath = str(tmp_path / "c.json")
    assert check.check_paths([str(d)], rules=["PIF118"],
                             cache=summaries.SummaryCache(cpath)) == []
    p.write_text(
        "import numpy as np\n\ndef stage(ack):\n"
        "    return np.zeros(ack.n)\n")
    stats = engine.RunStats()
    found = check.check_paths([str(d)], rules=["PIF118"], stats=stats,
                              cache=summaries.SummaryCache(cpath))
    assert stats.cache["misses"] == 1  # stale hash recomputed
    assert rule_ids(found) == ["PIF118"]


def test_cli_stats_json_shape(tmp_path, capsys):
    d = tmp_path / "serve"
    d.mkdir()
    (d / "snippet.py").write_text(
        "def stage(ack):\n    return bytearray(ack.n)\n")
    rc = check_cli_main([str(d), "--rule", "PIF118",
                         "--format", "json", "--stats"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    stats = doc["stats"]
    assert stats["files"] == 1
    for phase in ("parse", "callgraph", "summaries", "taint"):
        assert phase in stats["phases"]
    assert stats["rules"]["PIF118"]["findings"] == 1
    assert set(stats["cache"]) == {"hits", "misses", "path"}
    # the findings themselves still carry the flow path
    (rec,) = doc["findings"]
    assert len(rec["flow"]) >= 2


def test_cli_stats_human_table(tmp_path, capsys):
    d = tmp_path / "serve"
    d.mkdir()
    (d / "snippet.py").write_text("x = 1\n")
    assert check_cli_main([str(d), "--rule", "PIF118", "--stats"]) == 0
    out = capsys.readouterr().out
    assert "--stats" in out and "summaries" in out
    assert "PIF118" in out and "summary cache:" in out


def test_sarif_codeflows_for_taint_findings():
    findings = check.check_source(
        "pkg/serve/snippet.py",
        "import numpy as np\n\n"
        "def land(frame, buf):\n"
        "    return np.frombuffer(buf, np.float32, count=frame.width)\n",
        rules=["PIF118"])
    assert len(findings) == 1
    doc = json.loads(engine.to_sarif(findings))
    (result,) = doc["runs"][0]["results"]
    locs = result["codeFlows"][0]["threadFlows"][0]["locations"]
    assert len(locs) >= 2
    texts = [l["location"]["message"]["text"] for l in locs]
    assert any("width" in t for t in texts)
    assert "count/offset" in texts[-1]
    # non-interprocedural findings carry no codeFlows
    plain = check.check_source(
        "m.py", "import time\nt0 = time.perf_counter()\n",
        rules=["PIF102"])
    doc2 = json.loads(engine.to_sarif(plain))
    assert all("codeFlows" not in r for r in doc2["runs"][0]["results"])


def test_finding_flow_json_roundtrip():
    f = engine.Finding(
        rule="PIF118", path="a.py", line=3, col=0, message="m",
        flow=(("a.py", 3, "read"), ("b.py", 9, "spent")))
    rec = f.to_record()
    assert rec["flow"] == [["a.py", 3, "read"], ["b.py", 9, "spent"]]
    assert engine.Finding.from_record(rec) == f
    # findings without a flow serialize exactly as before (baseline
    # key and record stability)
    bare = engine.Finding(rule="PIF102", path="a.py", line=1, col=0,
                          message="m")
    assert "flow" not in bare.to_record()
