"""Four-step single-pass large-n path: kernel parity, VMEM budget
validation, plan-ladder crossover selection, sharded-path pickup, and
the bench's roofline accounting (interpret mode on the CPU backend; the
same code compiles for TPU — bench.py exercises that on hardware)."""

import numpy as np
import pytest

from cs87project_msolano2_tpu.ops.bits import bit_reverse_indices
from cs87project_msolano2_tpu.ops.pallas_fft import (
    VMEM_LIMIT_BYTES,
    fft_pi_layout_pallas2,
    fft_pi_layout_pallas_fourstep,
    fourstep_auto_cb,
    fourstep_vmem_bytes,
    long_range_grid,
    long_range_vmem_bytes,
)


def rand_planes(n, seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.standard_normal(n).astype(np.float32),
        rng.standard_normal(n).astype(np.float32),
    )


def to_complex(yr, yi):
    return np.asarray(yr).astype(np.complex128) + 1j * np.asarray(yi)


def np_pi_layout(x, n):
    return np.fft.fft(x.astype(np.complex128))[bit_reverse_indices(n)]


# ------------------------------------------------------- kernel parity


@pytest.mark.parametrize("n,tile,cb,tail,separable", [
    (1 << 12, 1 << 11, None, 128, True),     # R=2 (minimal long range)
    (1 << 13, 1 << 10, None, 128, True),     # qb == Q: QB=1 boundary
    (1 << 14, 1 << 11, 1 << 10, 128, True),  # QB=2: boundary drains both
    (1 << 15, 1 << 12, 1 << 10, 256, True),  # QB=4: in-phase slot waits
    (1 << 15, 1 << 12, 1 << 10, 256, False),  # dense-twiddle phase A
    (1 << 16, 1 << 13, None, 256, True),     # deeper R=8 pipeline
])
def test_fourstep_vs_numpy(n, tile, cb, tail, separable):
    xr, xi = rand_planes(n, seed=21)
    x = xr.astype(np.complex128) + 1j * xi
    yr, yi = fft_pi_layout_pallas_fourstep(
        xr, xi, tile=tile, cb=cb, tail=tail, separable=separable)
    err = np.max(np.abs(to_complex(yr, yi) - np_pi_layout(x, n))) / \
        np.max(np.abs(np_pi_layout(x, n)))
    assert err < 1e-5, (n, tile, cb, tail, separable, err)


def test_fourstep_matches_two_kernel_path():
    """Three-way parity: the single-pass fourstep pipeline, the
    two-kernel pallas2 path, and numpy must agree on the same input —
    the DMA-carry dataflow may not change a single value."""
    n, tile = 1 << 14, 1 << 12
    xr, xi = rand_planes(n, seed=22)
    x = xr.astype(np.complex128) + 1j * xi
    fr, fi = fft_pi_layout_pallas_fourstep(xr, xi, tile=tile, tail=128)
    tr, ti = fft_pi_layout_pallas2(xr, xi, tile=tile, tail=128)
    ref = np_pi_layout(x, n)
    scale = np.max(np.abs(ref))
    assert np.max(np.abs(to_complex(fr, fi) - ref)) / scale < 1e-5
    assert np.max(np.abs(to_complex(tr, ti) - ref)) / scale < 1e-5
    # fourstep vs pallas2 directly: identical stage math, tighter bound
    assert np.max(np.abs(to_complex(fr, fi) - to_complex(tr, ti))) / \
        scale < 1e-5


def test_fourstep_flagship_size():
    """The flagship n=2^20 shape end-to-end through the default
    (auto-cb, separable) configuration."""
    n = 1 << 20
    xr, xi = rand_planes(n, seed=23)
    x = xr.astype(np.complex128) + 1j * xi
    yr, yi = fft_pi_layout_pallas_fourstep(xr, xi)
    ref = np_pi_layout(x, n)
    err = np.max(np.abs(to_complex(yr, yi) - ref)) / np.max(np.abs(ref))
    assert err < 1e-5


@pytest.mark.slow
def test_fourstep_large_n_2_22():
    """Large-n reach: the acceptance shape (R=64 at tile=2^16) through
    the exact static-default parameters the plan layer serves."""
    n = 1 << 22
    xr, xi = rand_planes(n, seed=24)
    x = xr.astype(np.complex128) + 1j * xi
    yr, yi = fft_pi_layout_pallas_fourstep(xr, xi, tile=1 << 16, tail=256)
    ref = np_pi_layout(x, n)
    err = np.max(np.abs(to_complex(yr, yi) - ref)) / np.max(np.abs(ref))
    assert err < 1e-5


def test_fourstep_r1_fallback():
    """tile == n: no long-range phase; the tile grid serves directly."""
    n = 1 << 13
    xr, xi = rand_planes(n, seed=25)
    x = xr.astype(np.complex128) + 1j * xi
    yr, yi = fft_pi_layout_pallas_fourstep(xr, xi, tile=n, tail=128)
    ref = np_pi_layout(x, n)
    assert np.max(np.abs(to_complex(yr, yi) - ref)) / \
        np.max(np.abs(ref)) < 1e-5


# --------------------------------------------------- budget validation


def test_fourstep_cb_validation():
    xr, xi = rand_planes(1 << 13, seed=26)
    with pytest.raises(ValueError):  # cb does not divide tile
        fft_pi_layout_pallas_fourstep(xr, xi, tile=1 << 11, cb=768)
    with pytest.raises(ValueError, match="sublane"):
        # qb=4: neither a multiple of 8 nor the whole tile
        fft_pi_layout_pallas_fourstep(xr, xi, tile=1 << 11, cb=512)


def test_fourstep_vmem_budget_error_names_shape():
    """An explicit (R, cb) pair past the scoped-VMEM ceiling must fail
    with the pair named, before any lowering is attempted."""
    n, tile = 1 << 22, 1 << 14  # R = 256
    xr, xi = rand_planes(n, seed=27)
    assert fourstep_vmem_bytes(256, 1 << 13, tile) > VMEM_LIMIT_BYTES
    with pytest.raises(ValueError, match=r"R=256 x cb=8192"):
        fft_pi_layout_pallas_fourstep(xr, xi, tile=tile, cb=1 << 13,
                                      interpret=False)


def test_fourstep_auto_cb_budget():
    """The auto chooser must produce lowerable blocks through the
    acceptance range (2^21..2^24 at tile=2^16) and raise clearly when
    no legal block can fit."""
    for logn in (21, 22, 23, 24):
        cb = fourstep_auto_cb(1 << logn, 1 << 16)
        R = (1 << logn) >> 16
        assert cb % 128 == 0 and (cb // 128) % 8 == 0
        assert fourstep_vmem_bytes(R, cb, 1 << 16) <= VMEM_LIMIT_BYTES
    with pytest.raises(ValueError, match="infeasible"):
        fourstep_auto_cb(1 << 26, 1 << 14)  # R = 4096: nothing fits


def test_long_range_vmem_budget_error_names_pair():
    """Satellite: long_range_grid must reject a (R, cb) pair that passes
    the divisibility check but exceeds VMEM, naming the pair instead of
    deferring to a remote-compile failure."""
    import jax.numpy as jnp

    R, C = 512, 1 << 14
    xr = jnp.zeros((R, C), jnp.float32)
    assert long_range_vmem_bytes(R, 1 << 13) > VMEM_LIMIT_BYTES
    with pytest.raises(ValueError, match=r"R=512 x cb=8192"):
        long_range_grid(xr, xr, cb=1 << 13, interpret=False)
    # the auto chooser shrinks cb under the same budget instead
    assert long_range_vmem_bytes(
        R, min(C, 4096), separable=False) > VMEM_LIMIT_BYTES  # would blow
    # divisibility violations still raise their own error first
    with pytest.raises(ValueError, match="divide"):
        long_range_grid(xr, xr, cb=100)


def test_long_range_separable_matches_dense():
    """Satellite: the factored A/B twiddle reconstruction must agree
    with the dense-table path bit-for-bit at the output tolerance."""
    import jax.numpy as jnp

    R, C = 16, 1 << 10
    xr, xi = rand_planes(R * C, seed=28)
    x2r = jnp.asarray(xr.reshape(R, C))
    x2i = jnp.asarray(xi.reshape(R, C))
    dr, di = long_range_grid(x2r, x2i, cb=256, separable=False)
    sr, si = long_range_grid(x2r, x2i, cb=256, separable=True)
    scale = np.max(np.abs(to_complex(dr, di)))
    assert np.max(np.abs(to_complex(dr, di) - to_complex(sr, si))) / \
        scale < 1e-6


# ----------------------------------------------- ladder and crossover


def test_static_default_selects_fourstep_only_above_crossover():
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.plans import ladder

    def variant(n, kind="TPU v5e", layout="pi"):
        return ladder.static_default(
            plans.make_key(n, layout=layout, device_kind=kind))[0]

    assert variant(1 << 14) == "rows"
    assert variant(1 << 18) == "rql"
    assert variant(1 << 20) == "rql"  # below the crossover
    for logn in (21, 22, 24):
        assert variant(1 << logn) == "fourstep"
    # offline natural keeps the jnp path (interpret kernels cost minutes
    # for nothing); offline pi layout has no jnp equivalent
    assert variant(1 << 22, kind="cpu-interpret",
                   layout="natural") == "jnp"
    assert variant(1 << 22, kind="cpu-interpret") == "fourstep"
    assert ladder.FOURSTEP_MIN_N == 1 << 21
    # past fourstep's own feasibility bound (R >= 512 at tile=2^16 —
    # no legal column block fits VMEM) the static default serves the
    # hierarchical sixstep pipeline (tests/test_sixstep.py), never a
    # plan that raises on execute and no longer the silent rql fallback
    assert variant(1 << 25) == "sixstep"
    assert variant(1 << 26) == "sixstep"


def test_ladder_orders_fourstep_by_crossover():
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.plans import ladder

    below = ladder.candidates(
        plans.make_key(1 << 20, layout="pi", device_kind="TPU v5e"))
    above = ladder.candidates(
        plans.make_key(1 << 22, layout="pi", device_kind="TPU v5e"))
    assert below[0][0] == "fused"          # flagship leads below
    assert above[0][0] == "fourstep"       # fourstep leads above
    # fourstep is still raced below the crossover (a surprise win must
    # be observable), and fused never appears above it
    assert any(v == "fourstep" for v, _ in below)
    assert not any(v.startswith("fused") for v, _ in above)
    # every fourstep entry builds an executor (params are coherent)
    for v, p in above:
        if v == "fourstep":
            assert p["tile"] in (1 << 15, 1 << 16) and "separable" in p


def test_tune_sweep_reports_measured_crossover():
    """Per-n crossover selection: with an injected timer that makes the
    first candidate win at every n, the sweep's measured crossover is
    the first n whose ladder leads with fourstep."""
    import itertools

    from cs87project_msolano2_tpu import plans

    cnt = itertools.count()
    out, cross = plans.tune_sweep(
        [1 << 20, 1 << 22],
        timer=lambda fn, key: 1.0 + next(cnt) * 1e-3,
        allow_offline=True, persist=False, verbose=False)
    assert [p.key.n for p in out] == [1 << 20, 1 << 22]
    assert out[0].variant == "fused" and out[1].variant == "fourstep"
    assert cross == 1 << 22
    assert plans.fourstep_crossover(out) == cross
    assert plans.fourstep_crossover(out[:1]) is None
    # one n whose race fails outright is skipped, not fatal: the other
    # ns' winners (already tuned/persisted) survive the sweep
    from cs87project_msolano2_tpu.plans import ladder

    n_bad = 1 << 24
    bad_count = len(ladder.candidates(
        plans.make_key(n_bad, layout="pi")))

    def flaky_timer(fn, key, _c=itertools.count()):
        if key.n == n_bad:
            raise RuntimeError("RESOURCE_EXHAUSTED: scoped vmem")
        return 1.0 + next(_c) * 1e-3

    out2, cross2 = plans.tune_sweep(
        [1 << 22, n_bad], timer=flaky_timer,
        allow_offline=True, persist=False, verbose=False)
    assert [p.key.n for p in out2] == [1 << 22]
    assert cross2 == 1 << 22
    assert bad_count > 0  # the failed n had a real race to lose


def test_fourstep_plan_executes():
    """A fourstep Plan built by the ladder executor must run end-to-end
    (natural layout bakes the gather in)."""
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.plans.core import Plan

    n = 1 << 13
    key = plans.make_key(n, layout="natural")
    plan = Plan(key=key, variant="fourstep",
                params={"tile": 1 << 10, "tail": 128}, source="static")
    xr, xi = rand_planes(n, seed=29)
    yr, yi = plan.execute(xr, xi)
    ref = np.fft.fft(xr.astype(np.complex128) + 1j * xi)
    err = np.max(np.abs(to_complex(yr, yi) - ref)) / np.max(np.abs(ref))
    assert err < 1e-5


# ------------------------------------------------- sharded-path pickup


def test_tube_planned_matches_tube():
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.models.pi_fft import (
        funnel,
        tube,
        tube_planned,
    )

    n, p = 1 << 12, 4
    xr, xi = rand_planes(n, seed=30)
    fr, fi = funnel(jnp.asarray(xr), jnp.asarray(xi), p)
    ar, ai = tube_planned(fr, fi, n, p)
    br, bi = tube(fr, fi, n, p)
    scale = np.max(np.abs(to_complex(br, bi)))
    assert np.max(np.abs(to_complex(ar, ai) - to_complex(br, bi))) / \
        scale < 1e-5


def test_pi_fft_sharded_with_plan(devices8):
    """The sharded path with an explicit per-shard-shape plan must match
    the tables path (same pi-layout output, same sharding) — the wiring
    that lets each device's tube run the kernel family."""
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.parallel.pi_shard import pi_fft_sharded

    n, p = 1 << 13, 8
    mesh = Mesh(np.array(devices8[:p]), ("p",))
    xr, xi = rand_planes(n, seed=31)
    xj, yj = jnp.asarray(xr), jnp.asarray(xi)
    ref_r, ref_i = pi_fft_sharded(xj, yj, mesh)  # jnp tube (auto: small s)
    plan = plans.get_plan(plans.make_key(n // p, layout="pi"))
    assert plan.variant == "rows"
    kr, ki = pi_fft_sharded(xj, yj, mesh, plan=plan)
    scale = np.max(np.abs(to_complex(ref_r, ref_i)))
    assert np.max(np.abs(to_complex(kr, ki) - to_complex(ref_r, ref_i))) / \
        scale < 1e-5
    # plan=False pins the jnp tube explicitly
    pr, pi_ = pi_fft_sharded(xj, yj, mesh, plan=False)
    assert np.max(np.abs(to_complex(pr, pi_) -
                         to_complex(ref_r, ref_i))) / scale < 1e-6


# ---------------------------------------------------- bench / roofline


def test_roofline_utilization():
    from cs87project_msolano2_tpu.utils.roofline import (
        fft_min_hbm_bytes,
        hbm_peak_bytes_per_s,
        roofline_utilization,
    )

    assert fft_min_hbm_bytes(1 << 20) == 16 << 20
    assert hbm_peak_bytes_per_s("TPU v5e") == pytest.approx(819e9)
    assert hbm_peak_bytes_per_s("TPU v5 lite") == pytest.approx(819e9)
    assert hbm_peak_bytes_per_s("TPU v5p") == pytest.approx(2765e9)
    assert hbm_peak_bytes_per_s("cpu-interpret") is None
    # n=2^24 at 1 ms on v5e: 268 MB / 1 ms = 268 GB/s of 819 GB/s
    util = roofline_utilization(1 << 24, 1.0, "TPU v5e")
    assert util == pytest.approx((16 * (1 << 24)) / 1e-3 / 819e9)
    assert roofline_utilization(1 << 24, 0.0, "TPU v5e") is None
    assert roofline_utilization(1 << 24, 1.0, "unknown") is None


def test_bench_smoke_pipeline(capsys):
    """The CI rot check in-process: bench --smoke must emit one JSON
    record with the flagship fields, the per-row large-n fields, and
    the plan descriptions, entirely offline."""
    import json

    import bench

    assert bench.main(["--smoke"]) == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["smoke"] is True
    assert rec["metric"].startswith("fft1d_n2^12")
    assert rec["plan"]["variant"] == "rows"
    # the C baseline is full-N only: a toy-n ratio would be meaningless
    assert "vs_baseline" not in rec
    tag = f"n2^{bench.SMOKE_LARGE_LOGNS[0]}"
    assert f"{tag}_ms" in rec and f"{tag}_gflops" in rec
    assert f"{tag}_vs_xla" in rec  # per-row xla comparison (satellite)
    # carry-pass-aware roofline fields ride on every row (the ceiling
    # is plan-declared, so it exists even offline where util does not)
    assert rec[f"{tag}_roofline_ceiling"] == 1.0  # rows path: carry-free
    assert rec[f"{tag}_carry_passes"] == 0
    # the interpret-safe sixstep cell (tests/test_sixstep.py has the
    # kernel itself; this asserts the bench wiring end to end)
    assert rec["sixstep_smoke_plan"]["variant"] == "sixstep"
    assert rec["sixstep_smoke_roofline_ceiling"] == pytest.approx(
        1 / 3, abs=1e-3)
    assert "sixstep_smoke_ms" in rec
    assert "degraded" not in rec
