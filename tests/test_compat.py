"""utils/compat.py: the JAX version shims (shard_map kwarg spelling,
vma tracking, ShapeDtypeStruct vma) — both version branches of each,
exercised via monkeypatching so the suite covers the branch the
installed JAX does NOT take."""

import inspect

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs87project_msolano2_tpu.utils import compat


# ------------------------------------------------------ shard_map shim


def test_check_kw_matches_real_signature():
    """The kwarg the shim chose at import time must actually exist on
    the shard_map this JAX ships."""
    params = inspect.signature(compat._shard_map).parameters
    assert compat._CHECK_KW in params
    assert compat._CHECK_KW in ("check_vma", "check_rep")


@pytest.mark.parametrize("kw", ["check_vma", "check_rep"])
def test_shard_map_spells_checker_kwarg_for_each_branch(monkeypatch, kw):
    """Both JAX lines: current (check_vma) and 0.4.x (check_rep).  The
    shim must forward mesh/specs untouched and spell the checker flag
    the way the running JAX expects."""
    seen = {}

    def fake_shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
        seen.update(kwargs, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, f=f)
        return "wrapped"

    monkeypatch.setattr(compat, "_shard_map", fake_shard_map)
    monkeypatch.setattr(compat, "_CHECK_KW", kw)
    fn = object()
    out = compat.shard_map(fn, mesh="m", in_specs="i", out_specs="o",
                           check=False)
    assert out == "wrapped"
    assert seen["f"] is fn
    assert (seen["mesh"], seen["in_specs"], seen["out_specs"]) == \
        ("m", "i", "o")
    assert seen[kw] is False
    assert set(seen) == {"f", "mesh", "in_specs", "out_specs", kw}


def test_shard_map_shim_runs_on_real_mesh(devices8):
    """End-to-end through the REAL shard_map on the virtual CPU mesh:
    the chosen kwarg spelling is one the installed JAX accepts."""
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(devices8[:2]), ("x",))
    f = compat.shard_map(lambda a: a * 2, mesh=mesh, in_specs=P("x"),
                         out_specs=P("x"), check=True)
    x = jnp.arange(8, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x) * 2)


# ------------------------------------------------------------ vma shims


def test_vma_of_plain_values_is_none():
    assert compat.vma_of(np.ones(4)) is None
    assert compat.vma_of(jnp.ones(4)) is None  # no manual axes here
    assert compat.vma_of(3.5) is None


def test_vma_of_without_typeof_branch(monkeypatch):
    """The 0.4.x branch: no jax.typeof at all -> always None."""
    monkeypatch.delattr(jax, "typeof", raising=False)
    assert compat.vma_of(jnp.ones(4)) is None


def test_vma_of_typeof_raises_branch(monkeypatch):
    """typeof rejecting a value (plain host object) degrades to None."""
    def angry_typeof(x):
        raise TypeError("not a jax value")

    monkeypatch.setattr(jax, "typeof", angry_typeof, raising=False)
    assert compat.vma_of(object()) is None


def test_shape_struct_plain_and_vma():
    s = compat.shape_struct((4, 8), jnp.float32)
    assert s.shape == (4, 8) and s.dtype == jnp.float32
    # empty vma never touches the vma kwarg (0.4.x safe)
    s2 = compat.shape_struct((2,), jnp.float32, vma=None)
    assert s2.shape == (2,)


def test_shape_struct_vma_fallback_branch(monkeypatch):
    """A ShapeDtypeStruct without the vma kwarg (0.4.x) must not break
    the shim — it falls back to the plain struct."""
    class OldStruct:
        def __init__(self, shape, dtype):  # no vma kwarg
            self.shape, self.dtype = shape, dtype

    monkeypatch.setattr(jax, "ShapeDtypeStruct", OldStruct)
    s = compat.shape_struct((4,), jnp.float32, vma={"x"})
    assert isinstance(s, OldStruct) and s.shape == (4,)


def test_pvary_all_identity_branches(monkeypatch):
    arrs = [jnp.ones(4), jnp.zeros(4)]
    # falsy vma: identity regardless of jax version
    assert compat.pvary_all(arrs, None) == arrs
    assert compat.pvary_all(arrs, set()) == arrs
    # no jax.lax.pvary (0.4.x): identity even with a vma set
    monkeypatch.delattr(jax.lax, "pvary", raising=False)
    assert compat.pvary_all(arrs, {"x"}) == arrs


def test_pvary_all_applies_pvary(monkeypatch):
    calls = []

    def fake_pvary(a, axes):
        calls.append(axes)
        return a

    monkeypatch.setattr(jax.lax, "pvary", fake_pvary, raising=False)
    arrs = [jnp.ones(2), jnp.ones(3)]
    out = compat.pvary_all(arrs, {"x"})
    assert len(out) == 2
    assert calls == [("x",), ("x",)]
