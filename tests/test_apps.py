"""The spectral operation suite (docs/APPS.md): fused conv/corr,
streaming overlap-save/add, the PDE family, the served op path, the
metered fusion gate, and check rule PIF116."""

import asyncio
import json
import os

import numpy as np
import pytest

from cs87project_msolano2_tpu import obs
from cs87project_msolano2_tpu.apps.spectral import (
    OPS,
    check_op,
    circular_conv,
    fftconv,
    fftconv_unfused,
    fftcorr,
    kernel_spectrum,
    kernel_spectrum_cache_clear,
    numpy_oracle,
    solve_spectral_1d,
)
from cs87project_msolano2_tpu.apps.stream import (
    OverlapSave,
    block_candidates,
    block_cost,
    choose_block,
    chunk_count,
    overlap_add,
    overlap_save,
    overlap_save_journaled,
    overlap_save_stream,
    overlap_waste,
)
from cs87project_msolano2_tpu.obs import metrics
from cs87project_msolano2_tpu.serve import Dispatcher, ServeConfig
from cs87project_msolano2_tpu.serve.batcher import BatchRunner, GroupKey
from cs87project_msolano2_tpu.serve.dispatcher import ServeError
from cs87project_msolano2_tpu.serve.shapes import ShapeSpec, load_shapes
from cs87project_msolano2_tpu.utils.roofline import (
    spectral_hbm_bytes,
    spectral_min_hbm_bytes,
)

RNG = np.random.default_rng(7)
TOL = 1e-4


def rel_err(got, ref):
    return float(np.max(np.abs(np.asarray(got) - ref))
                 / max(np.max(np.abs(ref)), 1e-30))


@pytest.fixture
def obs_armed():
    owned = not obs.enabled()
    if owned:
        obs.enable()
    yield
    if owned:
        obs.disable()


# ------------------------------------------------------ fused spectral


class TestSpectral:
    @pytest.mark.parametrize("mode", ["full", "same", "valid"])
    @pytest.mark.parametrize("la,lv", [(200, 33), (256, 1), (100, 100),
                                       (512, 7), (8, 13), (33, 200)])
    def test_fftconv_matches_numpy(self, mode, la, lv):
        x = RNG.standard_normal(la).astype(np.float32)
        k = RNG.standard_normal(lv).astype(np.float32)
        ref = np.convolve(x.astype(np.float64), k.astype(np.float64),
                          mode)
        assert rel_err(fftconv(x, k, mode), ref) < TOL

    @pytest.mark.parametrize("mode", ["full", "same", "valid"])
    @pytest.mark.parametrize("la,lv", [(200, 33), (128, 5),
                                       (8, 13), (33, 200), (7, 12),
                                       (100, 100)])
    def test_fftcorr_matches_numpy(self, mode, la, lv):
        x = RNG.standard_normal(la).astype(np.float32)
        k = RNG.standard_normal(lv).astype(np.float32)
        ref = np.correlate(x.astype(np.float64),
                           k.astype(np.float64), mode)
        assert rel_err(fftcorr(x, k, mode), ref) < TOL

    def test_corr_conjugation_matters(self):
        # a shifted-delta kernel: conv shifts right, corr shifts left
        x = RNG.standard_normal(128).astype(np.float32)
        k = np.zeros(5, np.float32)
        k[3] = 1.0
        conv = fftconv(x, k, "full")
        corr = fftcorr(x, k, "full")
        assert not np.allclose(conv, corr, atol=1e-3)
        assert rel_err(corr, np.correlate(x.astype(np.float64), k,
                                          "full")) < TOL

    def test_circular_conv_is_the_served_primitive(self):
        n = 256
        x = RNG.standard_normal(n).astype(np.float32)
        k = RNG.standard_normal(n).astype(np.float32)
        got = circular_conv(x, k, "conv")
        ref = numpy_oracle("conv", x.astype(np.float64),
                           k.astype(np.float64), n)
        assert rel_err(got, ref) < TOL

    def test_circular_serves_any_length(self):
        # non-pow2 lengths are first-class plans now (docs/PLANS.md,
        # "Arbitrary n"); only degenerate n < 2 is refused
        x = RNG.standard_normal(100).astype(np.float32)
        k = RNG.standard_normal(3).astype(np.float32)
        got = circular_conv(x, k)
        ref = np.real(np.fft.ifft(np.fft.fft(x) * np.fft.fft(k, 100)))
        assert rel_err(got, ref.astype(np.float32)) < TOL
        with pytest.raises(ValueError, match="n=1 must be >= 2"):
            circular_conv(np.zeros(1, np.float32),
                          np.zeros(1, np.float32))

    def test_kernel_spectrum_cache_one_forward_transform(self,
                                                         obs_armed):
        kernel_spectrum_cache_clear()
        k = RNG.standard_normal(17).astype(np.float32)
        kernel_spectrum(k, 256)
        miss0 = metrics.counter_value("pifft_apps_kspec_cache_total",
                                      result="miss")
        hit0 = metrics.counter_value("pifft_apps_kspec_cache_total",
                                     result="hit")
        kernel_spectrum(k, 256)
        kernel_spectrum(np.array(k), 256)  # same VALUES, same entry
        assert metrics.counter_value("pifft_apps_kspec_cache_total",
                                     result="miss") == miss0
        assert metrics.counter_value("pifft_apps_kspec_cache_total",
                                     result="hit") == hit0 + 2
        # a different n (or kernel) is its own entry
        kernel_spectrum(k, 512)
        assert metrics.counter_value("pifft_apps_kspec_cache_total",
                                     result="miss") == miss0 + 1

    def test_solve_1d_oracle(self):
        n = 1 << 10
        f = RNG.standard_normal(n).astype(np.float32)
        ref = numpy_oracle("solve", f.astype(np.float64), None, n)
        assert rel_err(solve_spectral_1d(f), ref) < TOL

    def test_check_op_refuses_unknown(self):
        assert check_op("conv") == "conv"
        with pytest.raises(ValueError, match="warp"):
            check_op("warp")
        assert OPS == ("fft", "conv", "corr", "solve")


# --------------------------------------------------- the metered gate


class TestFusionMeter:
    def test_fused_at_floor_unfused_above(self, obs_armed):
        x = RNG.standard_normal(1000).astype(np.float32)
        k = RNG.standard_normal(25).astype(np.float32)
        n_pad = 1024

        def delta(fn):
            before = metrics.counter_value("pifft_hbm_bytes_total")
            y = fn(x, k)
            return y, int(metrics.counter_value(
                "pifft_hbm_bytes_total") - before)

        y_f, fused = delta(fftconv)
        y_u, unfused = delta(fftconv_unfused)
        floor = spectral_min_hbm_bytes("conv", n_pad)
        assert 0 < fused <= floor * 1.05
        assert unfused > floor * 1.05
        assert unfused == spectral_hbm_bytes("conv", n_pad,
                                             host_round_trips=1)
        np.testing.assert_allclose(y_f, y_u, atol=1e-3)

    def test_spectral_traffic_model_shapes(self):
        # conv reads signal + kernel spectrum + writes output; solve
        # reads and writes the field; a host round trip adds a full
        # spectrum write+read on top
        n = 1 << 12
        assert spectral_min_hbm_bytes("conv", n) \
            == 4 * (2 * n + 2 * (n // 2 + 1))
        assert spectral_min_hbm_bytes("solve", n) == 4 * 2 * n
        trip = 2 * 2 * 4 * (n // 2 + 1)
        assert spectral_hbm_bytes("conv", n, 1) \
            == spectral_min_hbm_bytes("conv", n) + trip
        with pytest.raises(ValueError, match="not in"):
            spectral_min_hbm_bytes("warp", n)


# --------------------------------------------------------- streaming


class TestOverlapSave:
    KERNEL = RNG.standard_normal(17).astype(np.float32)

    @pytest.mark.parametrize("n,block", [
        (300, 64),     # many chunks, non-divisible tail
        (64, 64),      # block == signal
        (30, 64),      # block > signal
        (100, 256),    # block > whole padded output (single chunk)
        (257, 32),     # odd length, small block
    ])
    def test_matches_direct_convolve(self, n, block):
        x = RNG.standard_normal(n).astype(np.float32)
        ref = np.convolve(x.astype(np.float64),
                          self.KERNEL.astype(np.float64), "full")
        assert rel_err(overlap_save(x, self.KERNEL, block=block),
                       ref) < TOL
        assert rel_err(overlap_add(x, self.KERNEL, block=block),
                       ref) < TOL

    def test_push_api_arbitrary_chunking(self):
        x = RNG.standard_normal(500).astype(np.float32)
        conv = OverlapSave(self.KERNEL, block=64)
        pieces = [conv.push(x[i:i + 41]) for i in range(0, 500, 41)]
        pieces.append(conv.flush())
        y = np.concatenate(pieces)
        ref = np.convolve(x.astype(np.float64),
                          self.KERNEL.astype(np.float64), "full")
        assert y.shape == ref.shape
        assert rel_err(y, ref) < TOL

    def test_generator_api_drains_incrementally(self):
        x = RNG.standard_normal(400).astype(np.float32)
        chunks = [x[i:i + 100] for i in range(0, 400, 100)]
        outs = list(overlap_save_stream(chunks, self.KERNEL, block=64))
        assert len(outs) > 1  # incremental, not one lump at the end
        ref = np.convolve(x.astype(np.float64),
                          self.KERNEL.astype(np.float64), "full")
        assert rel_err(np.concatenate(outs), ref) < TOL

    def test_one_plan_pair_for_all_chunks(self):
        # every chunk rides the same cached fused callable: the chunk
        # count grows, the compiled-program count does not
        x = RNG.standard_normal(1000).astype(np.float32)
        conv = OverlapSave(self.KERNEL, block=64)
        conv.push(x)
        conv.flush()
        assert conv.chunks == chunk_count(1000, 17, 64)

    def test_block_validation(self):
        # odd blocks have no r2c pack split; any EVEN block is now a
        # ladder plan (the any-length variants — docs/PLANS.md)
        with pytest.raises(ValueError, match="even"):
            OverlapSave(self.KERNEL, block=101)
        with pytest.raises(ValueError, match="kernel length"):
            OverlapSave(RNG.standard_normal(80).astype(np.float32),
                        block=64)

    def test_block_mixed_radix_accepted(self):
        """A 3*2^j block (the new half-octave candidates) streams
        correctly through the fused chunk pipeline."""
        x = RNG.standard_normal(1000).astype(np.float32)
        conv = OverlapSave(self.KERNEL, block=96)
        y = np.concatenate([conv.push(x), conv.flush()])
        ref = np.convolve(x.astype(np.float64),
                          self.KERNEL.astype(np.float64), "full")
        assert rel_err(y, ref) < TOL

    def test_block_choice_model(self):
        m = 33
        cands = block_candidates(m)
        # pow2 and 3*2^j half-octave blocks, nothing else — and every
        # candidate even (the r2c pack split) and deduplicated
        odd_parts = set()
        for b in cands:
            assert b % 2 == 0
            o = b
            while o % 2 == 0:
                o //= 2
            odd_parts.add(o)
        assert odd_parts <= {1, 3}
        assert 3 in odd_parts, cands  # the mixed sizes are raced
        assert len(set(cands)) == len(cands)
        assert cands == sorted(cands)
        assert cands[0] >= 2 * (m - 1)
        best = choose_block(m)
        assert block_cost(best, m) == min(block_cost(b, m)
                                          for b in cands)
        # waste shrinks as block grows; chunk count shrinks too
        assert overlap_waste(64, m) > overlap_waste(256, m)
        assert chunk_count(10_000, m, 64) > chunk_count(10_000, m, 512)

    def test_kill_mid_stream_resume(self, tmp_path):
        """The journaled variant resumes at the first chunk a kill
        took — recomputing only those, byte-identical results."""
        x = RNG.standard_normal(700).astype(np.float32)
        jp = str(tmp_path / "os.jsonl")
        ref = np.convolve(x.astype(np.float64),
                          self.KERNEL.astype(np.float64), "full")
        y1, computed1 = overlap_save_journaled(x, self.KERNEL, jp,
                                               block=128)
        total = chunk_count(700, 17, 128)
        assert computed1 == total
        assert rel_err(y1, ref) < TOL
        # simulate the kill: drop the last two chunk records (plus a
        # torn half-line, which the tolerant reader skips)
        with open(jp, encoding="utf-8") as fh:
            lines = fh.readlines()
        kept = [ln for ln in lines
                if f'"cell": "os:{total - 1}"' not in ln
                and f'"cell": "os:{total - 2}"' not in ln]
        with open(jp, "w", encoding="utf-8") as fh:
            fh.writelines(kept)
            fh.write('{"cell": "os:torn')  # the half-written tail
        y2, computed2 = overlap_save_journaled(x, self.KERNEL, jp,
                                               block=128)
        assert computed2 == 2
        np.testing.assert_array_equal(y1, y2)
        # a different configuration must REFUSE the journal — block
        # AND kernel (a same-length different kernel would otherwise
        # splice mixed-kernel chunks)
        with pytest.raises(ValueError, match="different"):
            overlap_save_journaled(x, self.KERNEL, jp, block=256)
        other_k = self.KERNEL + np.float32(1.0)
        with pytest.raises(ValueError, match="different"):
            overlap_save_journaled(x, other_k, jp, block=128)

    def test_resume_of_finished_journal_computes_nothing(self,
                                                         tmp_path):
        x = RNG.standard_normal(300).astype(np.float32)
        jp = str(tmp_path / "os.jsonl")
        y1, _ = overlap_save_journaled(x, self.KERNEL, jp, block=64)
        y2, computed = overlap_save_journaled(x, self.KERNEL, jp,
                                              block=64)
        assert computed == 0
        np.testing.assert_array_equal(y1, y2)


# ------------------------------------------------------- the PDE family


class TestPdeFamily:
    def test_poisson3d_shim_dispatches_through_family(self, devices8):
        """The refactored poisson3d is a THIN shim over apps/pde: the
        sharded solve still matches the full-grid family solve (one
        spectral pipeline, not two)."""
        import jax
        import jax.numpy as jnp

        from cs87project_msolano2_tpu.apps.pde import poisson_solve
        from cs87project_msolano2_tpu.parallel import (
            make_mesh,
            poisson_solve_sharded,
        )

        mesh = make_mesh(8)
        f = RNG.standard_normal((16, 16, 8)).astype(np.float32)
        f -= f.mean()
        u_sharded = np.asarray(jax.jit(
            lambda v: poisson_solve_sharded(v, mesh))(jnp.asarray(f)))
        u_family = np.asarray(poisson_solve(f))
        np.testing.assert_allclose(u_sharded, u_family, atol=1e-4)

    def test_helmholtz_sharded_vs_fullgrid(self, devices8):
        import jax
        import jax.numpy as jnp

        from cs87project_msolano2_tpu.apps.pde import (
            helmholtz_solve,
            helmholtz_solve_sharded,
        )
        from cs87project_msolano2_tpu.parallel import make_mesh

        mesh = make_mesh(8)
        f = RNG.standard_normal((16, 16, 8)).astype(np.float32)
        u_sh = np.asarray(jax.jit(
            lambda v: helmholtz_solve_sharded(v, mesh, alpha=3.0))(
                jnp.asarray(f)))
        u_fg = np.asarray(helmholtz_solve(f, 3.0))
        np.testing.assert_allclose(u_sh, u_fg, atol=1e-4)

    def test_heat_step_exact(self):
        from cs87project_msolano2_tpu.apps.pde import spectral_step

        f = RNG.standard_normal((16, 32)).astype(np.float32)
        k1 = np.fft.fftfreq(16) * 16
        k2 = np.fft.fftfreq(32) * 32
        ksq = k1[:, None] ** 2 + k2[None, :] ** 2
        ref = np.real(np.fft.ifft2(np.fft.fft2(f.astype(np.float64))
                                   * np.exp(-0.1 * ksq * 0.05)))
        got = np.asarray(spectral_step(f, nu=0.1, dt=0.05))
        assert rel_err(got, ref) < TOL

    def test_variable_helmholtz_converges(self):
        from cs87project_msolano2_tpu.apps.pde import (
            helmholtz_solve_variable,
        )

        f = RNG.standard_normal((32, 32)).astype(np.float32)
        alpha = (2.0 + 0.6 * np.cos(
            np.linspace(0, 2 * np.pi, 32))[:, None]
            * np.ones((1, 32))).astype(np.float32)
        u = np.asarray(helmholtz_solve_variable(f, alpha, iters=80))
        k = np.fft.fftfreq(32) * 32
        ksq = k[:, None] ** 2 + k[None, :] ** 2
        lap = np.real(np.fft.ifft2(np.fft.fft2(u.astype(np.float64))
                                   * (-ksq)))
        res = np.max(np.abs(alpha * u - lap - f)) / np.max(np.abs(f))
        assert res < 1e-3

    def test_helmholtz_validation(self):
        from cs87project_msolano2_tpu.apps.pde import (
            helmholtz_multiplier,
        )

        with pytest.raises(ValueError, match="> 0"):
            helmholtz_multiplier(0.0)


# ----------------------------------------------------- the served path


class TestServedOps:
    N = 512

    def _planes(self, count=1):
        return [(RNG.standard_normal(self.N).astype(np.float32),
                 RNG.standard_normal(self.N).astype(np.float32))
                for _ in range(count)]

    def test_op_group_label_and_identity(self):
        g = GroupKey(n=self.N, domain="r2c", op="conv")
        assert g.label() == f"{self.N}:natural:split3:r2c:conv"
        assert g != GroupKey(n=self.N, domain="r2c", op="corr")
        assert GroupKey(n=self.N).label() \
            == f"{self.N}:natural:split3"  # fft labels unchanged

    @pytest.mark.parametrize("op", ["conv", "corr", "solve"])
    @pytest.mark.parametrize("rung", [None, "jnp-fft", "numpy-ref"])
    def test_batch_runner_op_rungs_speak_the_op(self, op, rung):
        planes = self._planes()
        if op == "solve":
            planes = [(planes[0][0], np.zeros(self.N, np.float32))]
        out = BatchRunner().run(GroupKey(n=self.N, domain="r2c",
                                         op=op), planes, rung)
        ref = numpy_oracle(op, planes[0][0].astype(np.float64),
                           planes[0][1].astype(np.float64), self.N)
        assert rel_err(out.yr[0], ref) < TOL
        if rung is not None:
            assert out.plan_variant == rung

    def test_coalesced_conv_served_and_op_counted(self, obs_armed):
        k = 6
        inputs = self._planes(k)
        cfg = ServeConfig(max_wait_ms=25.0)

        async def main():
            async with Dispatcher(cfg) as d:
                resps = await asyncio.gather(*[
                    d.submit(xr, xi, op="conv") for xr, xi in inputs])
                return d, resps

        d, resps = asyncio.run(main())
        label = GroupKey(n=self.N, domain="r2c", op="conv").label()
        for (xr, xi), r in zip(inputs, resps):
            ref = numpy_oracle("conv", xr.astype(np.float64),
                               xi.astype(np.float64), self.N)
            assert rel_err(r.yr, ref) < TOL
        batches = metrics.counter_value("pifft_serve_batches_total",
                                        shape=label)
        assert 0 < batches < k
        assert metrics.counter_value("pifft_serve_ops_total",
                                     op="conv") >= k
        assert metrics.counter_value("pifft_apps_hbm_bytes_total",
                                     op="conv") > 0
        assert label in d.stats.summary()

    def test_degrade_tagged_on_fallback(self, obs_armed):
        from cs87project_msolano2_tpu.resilience import inject

        xr, xi = self._planes()[0]

        async def main():
            async with Dispatcher(ServeConfig()) as d:
                with inject("serve", "capacity", count=1):
                    return await d.submit(xr, xi, op="conv")

        resp = asyncio.run(main())
        assert resp.degraded
        assert any("jnp-fft" in t for t in resp.degrade)
        ref = numpy_oracle("conv", xr.astype(np.float64),
                           xi.astype(np.float64), self.N)
        assert rel_err(resp.yr, ref) < TOL  # degraded, still a conv

    def test_op_validation(self):
        xr, xi = self._planes()[0]

        async def run(**kw):
            async with Dispatcher(ServeConfig()) as d:
                return await d.submit(**kw)

        with pytest.raises(ServeError, match="not in"):
            asyncio.run(run(xr=xr, xi=xi, op="warp"))
        with pytest.raises(ServeError, match="kernel"):
            asyncio.run(run(xr=xr, op="conv"))
        with pytest.raises(ServeError, match="natural"):
            asyncio.run(run(xr=xr, xi=xi, op="conv", layout="pi"))
        with pytest.raises(ServeError, match="inverse"):
            asyncio.run(run(xr=xr, xi=xi, op="corr", inverse=True))
        with pytest.raises(ServeError, match="solve"):
            asyncio.run(run(xr=xr, xi=xi, op="solve"))

    def test_strict_shapes_op_aware(self):
        """A warmed conv shape serves conv but not corr at the same n
        — the op is part of the served identity."""
        spec = ShapeSpec(n=self.N, op="conv")
        xr, xi = self._planes()[0]

        async def main():
            async with Dispatcher(ServeConfig(strict_shapes=True),
                                  [spec]) as d:
                ok = await d.submit(xr, xi, op="conv")
                with pytest.raises(ServeError, match="not in the "
                                   "warmed set"):
                    await d.submit(xr, xi, op="corr")
                return ok

        resp = asyncio.run(main())
        assert resp.batch_size >= 1

    def test_solve_over_socket(self):
        from cs87project_msolano2_tpu.serve.protocol import (
            handle_connection,
            request_over_socket,
        )

        f = RNG.standard_normal(self.N).astype(np.float32)

        async def main():
            d = Dispatcher(ServeConfig())
            server = await asyncio.start_server(
                lambda r, w: handle_connection(d, r, w),
                "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            rep = await request_over_socket("127.0.0.1", port, f,
                                            op="solve")
            bad = await request_over_socket("127.0.0.1", port, f,
                                            op="warp")
            server.close()
            await server.wait_closed()
            await d.close()
            return rep, bad

        rep, bad = asyncio.run(main())
        assert rep["ok"]
        ref = numpy_oracle("solve", f.astype(np.float64), None,
                           self.N)
        assert rel_err(np.asarray(rep["yr"]), ref) < TOL
        assert not bad["ok"] and bad["error"]["type"] == "bad_request"

    def test_loadgen_op_cell(self, obs_armed):
        from cs87project_msolano2_tpu.serve.loadgen import (
            run_offered_load,
        )

        async def main():
            async with Dispatcher(ServeConfig(max_wait_ms=1.0)) as d:
                return await run_offered_load(
                    d, self.N, rps=200.0, duration_s=0.1, op="conv")

        row = asyncio.run(main())
        assert row["op"] == "conv"
        assert row["shape"].endswith(":conv")
        assert row["completed"] > 0 and row["failed"] == 0


# -------------------------------------------------- shapes / warm / CLI


class TestShapesAndWarm:
    def test_shape_spec_op_column(self):
        spec = ShapeSpec.from_record({"n": 1024, "op": "conv"})
        assert spec.op == "conv" and spec.domain == "r2c"
        assert spec.label() == "1024:natural:split3:r2c:conv"
        assert spec.key().domain == "r2c"
        assert ShapeSpec.from_record({"n": 64}).op == "fft"
        assert spec.to_record()["op"] == "conv"

    def test_unknown_op_refused_structured(self, tmp_path):
        with pytest.raises(ValueError, match="op='warp'"):
            ShapeSpec(n=64, op="warp")
        path = tmp_path / "shapes.jsonl"
        path.write_text('{"n": 64}\n{"n": 64, "op": "warp"}\n')
        with pytest.raises(ValueError, match="shapes.jsonl:2"):
            load_shapes(str(path))

    def test_warm_op_shape_warms_both_directions(self, tmp_path):
        from cs87project_msolano2_tpu.serve.shapes import warm

        plans_out = warm([ShapeSpec(n=256, op="conv")])
        assert plans_out[0].key.domain == "r2c"

    def test_plan_warm_shapes_cli_accepts_op(self, tmp_path, capsys):
        from cs87project_msolano2_tpu.cli import plan_main

        path = tmp_path / "shapes.jsonl"
        path.write_text('{"n": 256, "op": "conv"}\n{"n": 256}\n')
        assert plan_main(["warm", "--shapes", str(path)]) == 0
        out = capsys.readouterr().out
        assert "256:natural:split3:r2c:conv" in out
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"n": 256, "op": "warp"}\n')
        assert plan_main(["warm", "--shapes", str(bad)]) == 2
        assert "warp" in capsys.readouterr().err

    def test_apps_cli_demo(self, capsys):
        from cs87project_msolano2_tpu.apps.cli import apps_main

        assert apps_main(["conv", "-n", "512"]) == 0
        assert "conv" in capsys.readouterr().out


# ------------------------------------------------- bench + loader rows


class TestBenchAndLoader:
    def test_bench_conv_row_fields(self):
        import bench

        row = bench.measure_conv_row(10, smoke=True)
        assert row["conv2^10_op"] == "conv"
        assert row["conv2^10_ms"] > 0
        assert row["conv2^10_parity_relerr"] < TOL

    def test_bench_os_row_fields(self):
        import bench

        row = bench.measure_os_row(10, smoke=True)
        assert row["os2^10_op"] == "conv"
        assert row["os2^10_block"] == 1024
        assert row["os2^10_chunks"] == chunk_count(4096, 129, 1024)
        assert 0 < row["os2^10_overlap_waste"] < 1
        assert row["os2^10_parity_relerr"] < TOL

    def test_loader_parses_op_rows_and_backfills_fft(self, tmp_path):
        from cs87project_msolano2_tpu.analyze.loader import (
            bench_samples,
            load_bench_round,
        )

        rec = {"n": 99, "rc": 0, "parsed": {
            "metric": "x", "value": 1.0, "unit": "u",
            "conv2^12_ms": 0.5, "corr2^12_gflops": 2.0,
            "os2^13_chunks": 5, "solve2^10_ms": 0.1,
            "n2^13_ms": 1.0, "rfft2^13_ms": 0.6}}
        path = tmp_path / "BENCH_r99.json"
        path.write_text(json.dumps(rec))
        samples = bench_samples(load_bench_round(str(path)))
        by_metric = {s.metric: s for s in samples}
        assert by_metric["conv2^12_ms"].op == "conv"
        assert by_metric["conv2^12_ms"].n == 1 << 12
        assert by_metric["corr2^12_gflops"].op == "corr"
        assert by_metric["os2^13_chunks"].op == "conv"
        assert by_metric["os2^13_chunks"].n == 1 << 13
        assert by_metric["solve2^10_ms"].op == "solve"
        # everything op-less backfills "fft" — including the whole
        # committed trajectory (checked below on the real rounds)
        assert by_metric["n2^13_ms"].op == "fft"
        assert by_metric["rfft2^13_ms"].op == "fft"
        assert by_metric["rfft2^13_ms"].domain == "r2c"

    def test_committed_rounds_backfill_op(self):
        import glob

        from cs87project_msolano2_tpu.analyze.loader import (
            bench_samples,
            load_bench_rounds,
        )

        rounds = load_bench_rounds(sorted(glob.glob(os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(
                __file__))), "BENCH_r0*.json"))))
        assert rounds
        for rnd in rounds:
            for s in bench_samples(rnd):
                assert s.op == "fft"


# --------------------------------------------------------- rule PIF116


class TestPif116:
    def run_rule(self, path, src):
        from cs87project_msolano2_tpu.check.engine import check_source

        return check_source(path, src, rules=["PIF116"])

    POSITIVE = """
import numpy as np
import jax.numpy as jnp
from cs87project_msolano2_tpu.models.real import rfft_planes_fast, irfft_planes_fast

def filt(xp, kr, ki, n):
    ar, ai = rfft_planes_fast(xp)
    har = np.asarray(ar)
    hai = np.asarray(ai)
    pr = har * kr - hai * ki
    pi = har * ki + hai * kr
    return irfft_planes_fast(jnp.asarray(pr), jnp.asarray(pi), n=n)
"""

    def test_positive_host_round_trip(self):
        findings = self.run_rule("/x/apps/a.py", self.POSITIVE)
        assert len(findings) == 2
        assert all(f.rule == "PIF116" for f in findings)
        assert "round-trips through host" in findings[0].message

    def test_negative_fused_pipeline(self):
        src = """
import jax.numpy as jnp
def filt(xp, kr, ki, fwd, inv):
    ar, ai = fwd.fn(xp, jnp.zeros_like(xp))
    pr, pi = ar * kr - ai * ki, ar * ki + ai * kr
    return inv.fn(pr, pi)
"""
        assert not self.run_rule("/x/apps/a.py", src)

    def test_host_after_inverse_is_fine(self):
        src = """
import numpy as np
def filt(xp, fwd, inv):
    ar, ai = fwd.execute(xp, xp)
    yr, yi = inv.execute(ar, ai)
    return np.asarray(yr)
"""
        assert not self.run_rule("/x/serve/a.py", src)

    def test_branchy_path_still_caught(self):
        src = """
import numpy as np
def filt(xp, fwd, inv, debug):
    sr, si = fwd.execute(xp, xp)
    if debug:
        stash = np.square(sr)
    return inv.execute(sr, si)
"""
        findings = self.run_rule("/x/apps/a.py", src)
        assert len(findings) == 1

    def test_scope_and_exemptions(self):
        src = """
import numpy as np
def filt(xp, fwd, inv):
    sr, si = fwd.execute(xp, xp)
    h = np.asarray(sr)
    return inv.execute(h, si)
"""
        assert self.run_rule("/x/apps/a.py", src)
        assert not self.run_rule("/x/models/a.py", src)  # out of scope
        oracle = src.replace("def filt", "def conv_oracle")
        assert not self.run_rule("/x/apps/a.py", oracle)

    def test_noqa_with_reason(self):
        src = self.POSITIVE.replace(
            "har = np.asarray(ar)",
            "har = np.asarray(ar)  # pifft: noqa[PIF116]: test escape")
        findings = self.run_rule("/x/apps/a.py", src)
        assert len(findings) == 1  # only the un-noqa'd sibling line

    def test_shipped_apps_and_serve_clean(self):
        """The shipped packages carry zero PIF116 findings — the
        committed baseline stays EMPTY (the one sanctioned noqa is
        the unfused gate control, which must carry its reason)."""
        from cs87project_msolano2_tpu.check.engine import check_paths

        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        pkg = os.path.join(root, "cs87project_msolano2_tpu")
        findings = [f for f in check_paths(
            [os.path.join(pkg, "apps"), os.path.join(pkg, "serve")],
            rules=["PIF116"])]
        assert not findings, findings

    def test_unfused_control_noqa_carries_reason(self):
        from cs87project_msolano2_tpu.check.engine import collect_noqa

        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        spectral = os.path.join(root, "cs87project_msolano2_tpu",
                                "apps", "spectral.py")
        entries = [e for e in collect_noqa([spectral])
                   if "PIF116" in e["ids"]]
        assert entries and all(e["reason"] for e in entries)
