"""Half-spectrum real-input transforms (docs/REAL.md): Hermitian
symmetry, rfft/irfft parity and round trips across the ladder (fused
offline + slow-marked fourstep at 2^22), the domain plan-key semantics
(token round trip, old-schema refusal, stale-token store migration,
riding the cached c2c winner at n/2), the degrade-chain walk on the
r2c path down to the numpy rung, the domain-aware roofline traffic
model (the bytes-halved tentpole), serve-path coalescing of half-width
r2c requests, the batched/sharded real path, the analyze loader's
domain backfill, and the PIF110 check rule."""

import asyncio
import json

import numpy as np
import pytest

from cs87project_msolano2_tpu import plans, resilience
from cs87project_msolano2_tpu.models.real import (
    hermitian_merge,
    irfft,
    pack_real_planes,
    rfft,
)
from cs87project_msolano2_tpu.plans import cache as plan_cache
from cs87project_msolano2_tpu.plans import ladder
from cs87project_msolano2_tpu.plans.core import SCHEMA_VERSION, Plan, PlanKey


@pytest.fixture(autouse=True)
def fresh_memory_cache():
    plan_cache.clear(memory=True, disk=False)
    yield
    plan_cache.clear(memory=True, disk=False)


def real_input(n, seed=0, batch=()):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(batch + (n,)).astype(np.float32)


def rel_err(got, ref):
    return np.max(np.abs(np.asarray(got) - ref)) / np.max(np.abs(ref))


# ------------------------------------------------- parity + properties


@pytest.mark.parametrize("n", [2, 4, 64, 1024, 4096, 16384])
def test_rfft_parity_vs_numpy(n):
    x = real_input(n, seed=1)
    assert rel_err(rfft(x), np.fft.rfft(x.astype(np.float64))) < 1e-5


def test_rfft_batched_parity():
    x = real_input(512, seed=2, batch=(3, 5))
    ref = np.fft.rfft(x.astype(np.float64), axis=-1)
    assert rel_err(rfft(x), ref) < 1e-5


def test_rfft_hermitian_symmetry_property():
    """The property the half-spectrum exists because of: for random
    real input, the full spectrum is conjugate-symmetric
    (X[n-k] = conj(X[k])), the DC and Nyquist bins are real, and
    rfft is exactly the full spectrum's non-redundant prefix."""
    from cs87project_msolano2_tpu.models.fft import fft

    n = 2048
    x = real_input(n, seed=3)
    full = np.asarray(fft(x)).astype(np.complex128)
    half = np.asarray(rfft(x)).astype(np.complex128)
    scale = np.max(np.abs(full))
    k = np.arange(1, n)
    assert np.max(np.abs(full[n - k] - np.conj(full[k]))) / scale < 1e-5
    assert abs(full[0].imag) / scale < 1e-5          # DC is real
    assert abs(full[n // 2].imag) / scale < 1e-5     # Nyquist is real
    assert np.max(np.abs(half - full[:n // 2 + 1])) / scale < 1e-5
    # and the half-spectrum really is half-width
    assert half.shape == (n // 2 + 1,)


@pytest.mark.parametrize("n", [4, 256, 4096])
def test_rfft_irfft_roundtrip(n):
    x = real_input(n, seed=4)
    back = np.asarray(irfft(rfft(x)))
    assert np.max(np.abs(back - x)) < 1e-4


def test_irfft_parity_vs_numpy():
    n = 1024
    spec = np.fft.rfft(real_input(n, seed=5).astype(np.float64))
    ref = np.fft.irfft(spec, n=n)
    got = np.asarray(irfft(spec.astype(np.complex64)))
    assert np.max(np.abs(got - ref)) / np.max(np.abs(ref)) < 1e-4


def test_rfft_refuses_complex_input():
    with pytest.raises(ValueError, match="real"):
        rfft(np.zeros(8, np.complex64))


def test_pack_merge_building_blocks():
    """The O(n) passes in isolation: pack deinterleaves, merge applied
    to an exact packed FFT reproduces numpy.fft.rfft exactly."""
    n = 256
    x = real_input(n, seed=6)
    zr, zi = pack_real_planes(x)
    assert np.array_equal(np.asarray(zr), x[0::2])
    assert np.array_equal(np.asarray(zi), x[1::2])
    z = np.fft.fft(x[0::2].astype(np.float64)
                   + 1j * x[1::2].astype(np.float64))
    yr, yi = hermitian_merge(z.real.astype(np.float32),
                             z.imag.astype(np.float32), n)
    got = np.asarray(yr) + 1j * np.asarray(yi)
    assert rel_err(got, np.fft.rfft(x.astype(np.float64))) < 1e-6


# ------------------------------------------------- the ladder, wrapped


def test_rfft_rides_fused_kernel():
    """An r2c executor built on the fused single-pass kernel (the
    inner c2c at n/2 with interpret-safe tile/qb) matches numpy —
    the pack/Hermitian wrapping composes with the real kernel
    family, not just the jnp fallback."""
    n = 1 << 14  # inner fused c2c at 2^13
    key = plans.make_key(n, layout="natural", domain="r2c")
    fn = ladder.build_executor(key, "fused",
                               {"tile": 1 << 12, "qb": 8, "tail": 256})
    x = real_input(n, seed=7)
    yr, yi = fn(x, np.zeros_like(x))
    got = np.asarray(yr) + 1j * np.asarray(yi)
    assert rel_err(got, np.fft.rfft(x.astype(np.float64))) < 1e-5


@pytest.mark.slow
def test_rfft_rides_fourstep_kernel_2_22():
    """The large-n rung: r2c at 2^22 over the fourstep HBM-carry
    pipeline at 2^21 (interpret mode — the same code compiles for
    TPU; the tuned-path acceptance bound is rel err <= 1e-5)."""
    n = 1 << 22
    key = plans.make_key(n, layout="natural", domain="r2c")
    fn = ladder.build_executor(
        key, "fourstep",
        {"tile": 1 << 16, "cb": None, "tail": 256, "separable": True})
    x = real_input(n, seed=8)
    yr, yi = fn(x, np.zeros_like(x))
    got = np.asarray(yr) + 1j * np.asarray(yi)
    assert rel_err(got, np.fft.rfft(x.astype(np.float64))) < 1e-5


# ----------------------------------------------------- plan-key domain


def test_plan_key_domain_token_round_trip():
    for key in (
        plans.make_key(1024, layout="natural", domain="r2c",
                       device_kind="TPU test-kind"),
        plans.make_key(4096, (8,), layout="natural", domain="c2r",
                       device_kind="TPU test-kind"),
        plans.make_key(512),
    ):
        assert PlanKey.from_token(key.token()) == key
    assert plans.make_key(512).domain == "c2c"


def test_plan_key_domain_validation():
    with pytest.raises(ValueError, match="domain"):
        plans.make_key(512, domain="half")
    with pytest.raises(ValueError, match="natural"):
        plans.make_key(512, layout="pi", domain="r2c")
    # odd n is served by the direct any-length real path now
    # (docs/PLANS.md "Arbitrary n"); only degenerate n is refused
    assert plans.make_key(9, domain="r2c").n == 9
    with pytest.raises(ValueError, match="n >= 2"):
        plans.make_key(1, domain="r2c")


def test_plan_key_io_shapes():
    k = plans.make_key(1024, (4,), layout="natural", domain="r2c")
    assert k.input_shape() == (4, 1024) and k.output_width() == 513
    k = plans.make_key(1024, layout="natural", domain="c2r")
    assert k.input_shape() == (513,) and k.output_width() == 1024
    k = plans.make_key(1024)
    assert k.input_shape() == (1024,) and k.output_width() == 1024


def test_old_schema_token_is_refused():
    """A pre-domain (schema 1) token must be refused cleanly — the
    field it lacks is compile-relevant, so guessing would alias a
    half-spectrum plan onto a c2c program."""
    old = json.dumps({
        "v": SCHEMA_VERSION - 1, "device_kind": "TPU test-kind",
        "n": 1024, "batch": [], "layout": "pi", "dtype": "float32",
        "precision": "split3"}, sort_keys=True, separators=(",", ":"))
    with pytest.raises(ValueError, match="schema"):
        PlanKey.from_token(old)


def test_stale_tokens_in_disk_store_skipped_with_one_warn(
        tmp_path, monkeypatch, capsys):
    """Plan-cache migration hardening: a current-schema store carrying
    stale (pre-domain) tokens serves every valid entry, skips the
    stale ones with ONE plans.warn — not a crash, not a silent wipe —
    and `plan show` survives the same file."""
    monkeypatch.setenv("PIFFT_PLAN_CACHE", str(tmp_path))
    key = plans.make_key(4096, (16,), device_kind="TPU test-kind")
    plan_cache.store(Plan(key=key, variant="rows",
                          params={"tail": 256}, source="tuned", ms=0.5))
    path = plan_cache.store_path(key.device_kind)
    with open(path) as fh:
        data = json.load(fh)
    stale_token = json.dumps({
        "v": SCHEMA_VERSION - 1, "device_kind": "TPU test-kind",
        "n": 2048, "batch": [], "layout": "pi", "dtype": "float32",
        "precision": "split3"}, sort_keys=True, separators=(",", ":"))
    data["plans"][stale_token] = {"variant": "rql", "params": {},
                                  "ms": 0.2}
    with open(path, "w") as fh:
        json.dump(data, fh)
    plan_cache.clear(memory=True, disk=False)
    plan_cache._STALE_WARNED.clear()
    # the valid entry still serves from disk
    hit = plan_cache.lookup(key)
    assert hit is not None and hit.variant == "rows"
    err = capsys.readouterr().err
    assert err.count("stale-schema") == 1
    assert "skipped 1" in err
    # repeat loads do not repeat the warn (once per store per process)
    plan_cache.clear(memory=True, disk=False)
    assert plan_cache.lookup(key) is not None
    assert "stale-schema" not in capsys.readouterr().err
    # and the CLI store listing survives the stale token
    from cs87project_msolano2_tpu.cli import main

    monkeypatch.setattr(plans, "current_device_kind",
                        lambda: "TPU test-kind")
    assert main(["plan", "show"]) == 0
    out = capsys.readouterr().out
    assert "domain=c2c" in out and "n=4096" in out


def test_r2c_plan_rides_cached_c2c_winner():
    """The tentpole contract: a tuned c2c winner at n/2 serves the r2c
    key at n — same variant and params, no extra race, memoized under
    its own domain token."""
    kind = plans.current_device_kind()
    inner = plans.make_key(2048, device_kind=kind)
    plan_cache.memoize(Plan(key=inner, variant="rql",
                            params={"tile": 1 << 16, "cb": None,
                                    "tail": 256},
                            source="tuned", ms=0.1))
    key = plans.make_key(4096, layout="natural", domain="r2c",
                         device_kind=kind)
    plan = plans.get_plan(key)
    assert plan.variant == "rql" and plan.source == "tuned"
    assert plan.params == {"tile": 1 << 16, "cb": None, "tail": 256}
    assert plan.ms is None  # the inner timing is not the real path's
    assert plan_cache.lookup(key) is plan  # memoized under the domain


def test_r2c_static_default_and_execute():
    plan = plans.plan_for((1024,), layout="natural", domain="r2c")
    assert plan.source == "static"
    x = real_input(1024, seed=9)
    yr, yi = plan.execute(x, np.zeros_like(x))
    got = np.asarray(yr) + 1j * np.asarray(yi)
    assert got.shape == (513,)
    assert rel_err(got, np.fft.rfft(x.astype(np.float64))) < 1e-5


def test_execute_inverse_refused_on_real_domains():
    plan = plans.plan_for((1024,), layout="natural", domain="r2c")
    with pytest.raises(ValueError, match="directional"):
        plan.execute_inverse(np.zeros(513, np.float32),
                             np.zeros(513, np.float32))


def test_r2c_candidates_mirror_half_length_c2c():
    key = PlanKey(device_kind="TPU test-kind", n=1 << 21, batch=(),
                  layout="natural", dtype="float32", precision="split3",
                  domain="r2c")
    sub = ladder.c2c_subkey(key)
    assert sub.n == 1 << 20 and sub.domain == "c2c"
    assert ladder.candidates(key) == ladder.candidates(sub)
    assert ladder.static_default(key) == ladder.static_default(sub)


# ------------------------------------------------------- degradation


def test_r2c_degrade_walk_ends_at_numpy_rung(monkeypatch, capsys):
    """The satellite walk: the kernel path AND the jnp escape rung die
    of CAPACITY; the chain lands on the numpy rung — which speaks
    rfft natively — with degraded:true, the right answer, and the
    skipped rungs recorded."""
    import jax.numpy as jnp

    n = 1 << 10
    x = real_input(n, seed=10)
    ref = np.fft.rfft(x.astype(np.float64))

    def boom(*a, **kw):
        raise RuntimeError("RESOURCE_EXHAUSTED: injected jnp death")

    with resilience.inject("tube", "capacity"):
        # the r2c jnp rung speaks rfft natively (docs/REAL.md) — kill
        # exactly that entry point so the walk must go one rung lower
        monkeypatch.setattr(jnp.fft, "rfft", boom)
        plan = plans.get_plan(
            plans.make_key(n, layout="natural", domain="r2c"))
        yr, yi = plan.execute(x, np.zeros_like(x))
    got = np.asarray(yr) + 1j * np.asarray(yi)
    assert rel_err(got, ref) < 1e-5
    assert plan.degraded is True
    assert plan.demotions[-1]["to"] == "numpy-ref"
    skipped = " ".join(plan.demotions[-1].get("skipped", []))
    assert "jnp-fft" in skipped
    assert "DEGRADED" in capsys.readouterr().err


def test_c2r_jnp_rung_parity():
    from cs87project_msolano2_tpu.resilience.degrade import build_rung

    n = 512
    spec = np.fft.rfft(real_input(n, seed=11).astype(np.float64))
    key = plans.make_key(n, layout="natural", domain="c2r")
    yr, _ = build_rung(key, "jnp-fft")(
        spec.real.astype(np.float32), spec.imag.astype(np.float32))
    ref = np.fft.irfft(spec, n=n)
    assert np.max(np.abs(np.asarray(yr) - ref)) < 1e-4


# ---------------------------------------------------------- roofline


def test_roofline_domain_bytes_halved():
    from cs87project_msolano2_tpu.utils.roofline import (
        fft_hbm_bytes,
        fft_min_hbm_bytes,
    )

    n = 1 << 20
    assert fft_min_hbm_bytes(n) == 16 * n
    assert fft_min_hbm_bytes(n, "r2c") == 8 * n
    assert fft_min_hbm_bytes(n, "c2r") == 8 * n
    # the halving holds carry pass for carry pass
    for p in (0, 1, 2):
        assert fft_hbm_bytes(n, p, "r2c") * 2 == fft_hbm_bytes(n, p)


def test_roofline_meter_charges_half_for_r2c():
    """The enforced tentpole: the metered pifft_hbm_bytes_total delta
    for an r2c measurement is EXACTLY half the c2c one at equal n and
    equal carry passes."""
    from cs87project_msolano2_tpu import obs
    from cs87project_msolano2_tpu.obs import metrics
    from cs87project_msolano2_tpu.utils.roofline import (
        roofline_utilization,
    )

    obs.enable()
    try:
        metrics.reset()
        roofline_utilization(1 << 16, 1.0, "TPU v5e", carry_passes=1)
        c2c = metrics.counter_value("pifft_hbm_bytes_total")
        roofline_utilization(1 << 16, 1.0, "TPU v5e", carry_passes=1,
                             domain="r2c")
        r2c = metrics.counter_value("pifft_hbm_bytes_total") - c2c
        assert c2c == 2 * r2c > 0
        # the utilization figure reads against the halved floor
        u_c2c = roofline_utilization(1 << 16, 1.0, "TPU v5e")
        u_r2c = roofline_utilization(1 << 16, 1.0, "TPU v5e",
                                     domain="r2c")
        assert u_c2c == pytest.approx(2 * u_r2c)
    finally:
        obs.disable()


# ------------------------------------------------------------- serve


def run_async(coro, timeout_s=120.0):
    async def bounded():
        return await asyncio.wait_for(coro, timeout=timeout_s)

    return asyncio.run(bounded())


def test_serve_r2c_requests_coalesce_half_width():
    """The serving acceptance: concurrent r2c requests coalesce into
    fewer (half-width) kernel invocations, every response carries its
    own half-spectrum verified against numpy.fft.rfft, and the SLO
    row is domain-tagged."""
    from cs87project_msolano2_tpu.serve import Dispatcher, ServeConfig

    n, k = 256, 9
    inputs = [real_input(n, seed=20 + i) for i in range(k)]

    async def main():
        cfg = ServeConfig(max_batch=8, max_wait_ms=50.0)
        async with Dispatcher(cfg) as d:
            resps = await asyncio.gather(
                *(d.submit(x, domain="r2c") for x in inputs))
            return d, resps

    d, resps = run_async(main())
    label = f"{n}:natural:split3:r2c"
    row = d.stats.summary()[label]
    assert row["requests"] == k
    assert 0 < row["batches"] < k  # coalescing happened
    for x, resp in zip(inputs, resps):
        got = np.asarray(resp.yr) + 1j * np.asarray(resp.yi)
        assert got.shape == (n // 2 + 1,)  # half-width, not padded back
        assert rel_err(got, np.fft.rfft(x.astype(np.float64))) < 1e-4
        assert not resp.degraded


def test_serve_r2c_validation():
    from cs87project_msolano2_tpu.serve import Dispatcher, ServeError

    async def main():
        async with Dispatcher() as d:
            x = real_input(256, seed=30)
            # omitted xi is fine for r2c
            ok = await d.submit(x, domain="r2c")
            with pytest.raises(ServeError, match="nonzero imaginary"):
                await d.submit(x, np.ones_like(x), domain="r2c")
            with pytest.raises(ServeError, match="both planes"):
                await d.submit(x, None)
            with pytest.raises(ServeError, match="conj trick|inverse"):
                await d.submit(x, domain="r2c", inverse=True)
            with pytest.raises(ServeError, match="domain"):
                await d.submit(x, np.zeros_like(x), domain="zzz")
            return ok

    ok = run_async(main())
    assert np.asarray(ok.yr).shape == (129,)


def test_serve_c2r_round_trip():
    from cs87project_msolano2_tpu.serve import Dispatcher

    n = 256
    x = real_input(n, seed=31)
    spec = np.fft.rfft(x.astype(np.float64))

    async def main():
        async with Dispatcher() as d:
            return await d.submit(spec.real.astype(np.float32),
                                  spec.imag.astype(np.float32),
                                  domain="c2r")

    resp = run_async(main())
    assert np.asarray(resp.yr).shape == (n,)
    assert np.max(np.abs(np.asarray(resp.yr) - x)) < 1e-4


def test_shape_spec_domain_parsing(tmp_path):
    from cs87project_msolano2_tpu.serve import ShapeSpec, load_shapes

    p = tmp_path / "shapes.jsonl"
    p.write_text('{"n": 1024, "domain": "r2c"}\n'
                 '{"n": 1024}\n')
    specs = load_shapes(str(p))
    assert specs[0].domain == "r2c" and specs[1].domain == "c2c"
    assert specs[0].label() == "1024:natural:split3:r2c"
    assert specs[0].key().domain == "r2c"
    assert specs[0] != specs[1]  # domains never alias a warm slot
    with pytest.raises(ValueError, match="domain"):
        ShapeSpec(n=512, domain="zzz")
    with pytest.raises(ValueError, match="natural"):
        ShapeSpec(n=512, layout="pi", domain="r2c")


def test_serve_smoke_with_mixed_domain_shapes(tmp_path, capsys):
    """The make rfft-smoke serving leg, in-process: an r2c burst spec
    first (so the coalescing assertion runs on the half-spectrum
    group) plus c2c mixed traffic — zero schema-invalid events."""
    from cs87project_msolano2_tpu.serve.cli import serve_main

    p = tmp_path / "mixed.jsonl"
    p.write_text('{"n": 512, "domain": "r2c"}\n{"n": 512}\n')
    rc = serve_main(["--smoke", "-k", "6", "--shapes", str(p),
                     "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0, out["problems"]
    assert out["ok"] is True
    assert 0 < out["same_shape_batches"] < out["same_shape_requests"]
    assert out["schema_invalid_events"] == 0


# ---------------------------------------------------- batched/sharded


def test_rfft_batched_sharded_parity():
    from cs87project_msolano2_tpu.parallel.batched import (
        rfft_batched_sharded,
    )
    from cs87project_msolano2_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8, axis="data")
    x = real_input(256, seed=40, batch=(16,))
    y = np.asarray(rfft_batched_sharded(x, mesh, axis="data"))
    ref = np.fft.rfft(x.astype(np.float64), axis=-1)
    assert y.shape == (16, 129)
    assert rel_err(y, ref) < 1e-5


def test_batched_planes_domain_rejects_inverse():
    from cs87project_msolano2_tpu.parallel.batched import (
        fft_batched_planes,
    )
    from cs87project_msolano2_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8, axis="data")
    x = real_input(256, batch=(8,))
    with pytest.raises(ValueError, match="c2r"):
        fft_batched_planes(x, np.zeros_like(x), mesh, axis="data",
                           inverse=True, domain="r2c")


# ------------------------------------------------------------ analyze


def test_loader_backfills_domain(tmp_path):
    """Records without a domain parse as c2c (the committed
    BENCH_r01-r06 trajectory keeps working); rfft2^K rows tag r2c
    with the same n."""
    from cs87project_msolano2_tpu.analyze.loader import (
        bench_samples,
        load_bench_round,
    )

    p = tmp_path / "BENCH_r99.json"
    p.write_text(json.dumps({
        "n": 99, "rc": 0,
        "parsed": {"metric": "g", "value": 1.0, "unit": "GFLOP/s",
                   "n2^13_gflops": 2.0, "rfft2^13_gflops": 1.2,
                   "smoke": True}}))
    rnd = load_bench_round(str(p))
    by_metric = {s.metric: s for s in bench_samples(rnd)}
    assert by_metric["n2^13_gflops"].domain == "c2c"
    assert by_metric["rfft2^13_gflops"].domain == "r2c"
    assert by_metric["rfft2^13_gflops"].n == 1 << 13
    assert by_metric["g"].domain == "c2c"
    # the committed pre-domain trajectory still parses
    committed = load_bench_round("BENCH_r01.json")
    assert committed.metrics
    assert all(s.domain == "c2c" for s in bench_samples(committed))


# ------------------------------------------------------------- PIF110


def test_pif110_flags_full_fft_on_provably_real_input():
    from cs87project_msolano2_tpu import check

    code = """
import numpy as np
import jax.numpy as jnp

def hot(x, rng):
    a = jnp.fft.fft(jnp.real(x))
    b = np.fft.fft(x.real)
    c = jnp.fft.fft(x.astype(jnp.float32))
    d = jnp.fft.fft(rng.standard_normal(64))
    xr = np.real(x)
    e = np.fft.fft(xr)
    return a, b, c, d, e
"""
    found = check.check_source("/repo/serve/hot.py", code,
                               rules=["PIF110"])
    assert len(found) == 5
    assert all(f.rule == "PIF110" for f in found)


def test_pif110_negative_and_scope_and_noqa():
    from cs87project_msolano2_tpu import check

    code = """
import numpy as np
import jax.numpy as jnp

def paths(x, xr):
    ok1 = jnp.fft.fft(x)                 # not provably real
    ok2 = jnp.fft.rfft(jnp.real(x))      # already half-spectrum
    ok3 = np.fft.fft(xr.astype(np.complex128) + 1j)  # complex promo
    bad = jnp.fft.fft(jnp.real(x))  # pifft: noqa[PIF110]
    return ok1, ok2, ok3, bad
"""
    assert check.check_source("/repo/parallel/p.py", code,
                              rules=["PIF110"]) == []
    flagged = "def f(x):\n    import jax.numpy as jnp\n" \
              "    return jnp.fft.fft(jnp.real(x))\n"
    # include-scoped: the same pattern outside serve/parallel passes
    assert check.check_source("/repo/models/m.py", flagged,
                              rules=["PIF110"]) == []
    assert check.check_source("/repo/tests/t.py", flagged,
                              rules=["PIF110"]) == []
    assert len(check.check_source("/repo/serve/s.py", flagged,
                                  rules=["PIF110"])) == 1


# ----------------------------------------------------------- cli/bench


def test_cli_plan_warm_domain_validation(capsys):
    from cs87project_msolano2_tpu.cli import main

    # pi layout + r2c is a key-validation error, reported not raised
    assert main(["plan", "warm", "-n", "2^10", "--domain", "r2c"]) == 2
    assert "natural" in capsys.readouterr().err
    # valid combo still refuses offline tuning (exit 2, like c2c)
    assert main(["plan", "warm", "-n", "2^10", "--layout", "natural",
                 "--domain", "r2c"]) == 2
    assert "offline" in capsys.readouterr().err


def test_bench_rfft_row_smoke():
    """The bench rfft cell end to end (offline smoke sizes): the row
    reports ms/gflops/plan/domain and numpy parity."""
    import bench

    row = bench.measure_rfft_row(10, smoke=True)
    assert row["rfft2^10_ms"] > 0
    assert row["rfft2^10_domain"] == "r2c"
    assert row["rfft2^10_parity_relerr"] < 1e-5
    assert row["rfft2^10_plan"]["variant"]
