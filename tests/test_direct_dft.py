"""Direct-DFT einsum model tests (north star: 'each output bin's
independent partial sum as a vmap'd complex einsum'; BASELINE.json
config 1 is the N=1024 float64 CPU reference run)."""

import numpy as np
import pytest

from cs87project_msolano2_tpu.models.direct_dft import (
    MAX_N,
    dft_direct,
    dft_direct_pi,
    dft_matrix,
)
from cs87project_msolano2_tpu.utils.verify import pi_layout_to_natural, rel_err


def rand_c64(n, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64
    )


def test_config1_n1024_float64():
    x = rand_c64(1024, seed=1).astype(np.complex128)
    y = np.asarray(dft_direct(x, dtype=np.complex128))
    assert rel_err(y, np.fft.fft(x)) < 1e-12  # float64 path


@pytest.mark.parametrize("n", [8, 256, 1024])
def test_dft_direct_vs_numpy(n):
    x = rand_c64(n, seed=2)
    assert rel_err(np.asarray(dft_direct(x)),
                   np.fft.fft(x.astype(np.complex128))) < 1e-4


@pytest.mark.parametrize("p", [1, 4, 64])
def test_dft_direct_pi_layout_and_p_invariance(p):
    n = 1024
    x = rand_c64(n, seed=3)
    y = np.asarray(dft_direct_pi(x, p))
    nat = pi_layout_to_natural(y)
    assert rel_err(nat, np.fft.fft(x.astype(np.complex128))) < 1e-4
    base = np.asarray(dft_direct_pi(x, 1))
    assert np.allclose(y, base, atol=1e-5)


def test_pi_layout_matches_butterfly_models():
    """Same pi layout as the funnel/tube models — the whole verification
    stack (gather, golden, cross-backend) applies unchanged."""
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.models.pi_fft import pi_fft_pi_layout

    n, p = 512, 8
    x = rand_c64(n, seed=4)
    yr, yi = pi_fft_pi_layout(
        jnp.asarray(x.real), jnp.asarray(x.imag), p
    )
    butterfly = np.asarray(yr) + 1j * np.asarray(yi)
    einsum = np.asarray(dft_direct_pi(x, p))
    assert rel_err(einsum, butterfly) < 1e-4


def test_dft_direct_pi_planes_matches_complex():
    from cs87project_msolano2_tpu.models.direct_dft import dft_direct_pi_planes

    n, p = 512, 8
    x = rand_c64(n, seed=5)
    yr, yi = dft_direct_pi_planes(x.real, x.imag, p)
    ref = np.asarray(dft_direct_pi(x, p))
    got = np.asarray(yr) + 1j * np.asarray(yi)
    assert rel_err(got, ref.astype(np.complex128)) < 1e-4


def test_max_n_guard():
    with pytest.raises(ValueError):
        dft_matrix(MAX_N * 2)


def test_einsum_backend_golden():
    from cs87project_msolano2_tpu.backends.registry import get_backend
    from cs87project_msolano2_tpu.utils import verify

    res = get_backend("einsum").run(verify.golden_input(), 4)
    nat = verify.pi_layout_to_natural(res.out)
    # einsum accumulates differently; golden values are exact integers but
    # float32 matmul may not hit them bit-exactly -> tolerance check
    assert verify.max_abs_err(nat, verify.golden_expected()) < 1e-4
    assert res.funnel_ms == 0.0 and res.tube_ms == res.total_ms
