"""Direct-DFT einsum model tests (north star: 'each output bin's
independent partial sum as a vmap'd complex einsum'; BASELINE.json
config 1 is the N=1024 float64 CPU reference run)."""

import numpy as np
import pytest

from cs87project_msolano2_tpu.models.direct_dft import (
    MAX_N,
    dft_direct,
    dft_direct_pi,
    dft_matrix,
)
from cs87project_msolano2_tpu.utils.verify import pi_layout_to_natural, rel_err


def rand_c64(n, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64
    )


def test_config1_n1024_float64():
    x = rand_c64(1024, seed=1).astype(np.complex128)
    y = np.asarray(dft_direct(x, dtype=np.complex128))
    assert rel_err(y, np.fft.fft(x)) < 1e-12  # float64 path


@pytest.mark.parametrize("n", [8, 256, 1024])
def test_dft_direct_vs_numpy(n):
    x = rand_c64(n, seed=2)
    assert rel_err(np.asarray(dft_direct(x)),
                   np.fft.fft(x.astype(np.complex128))) < 1e-4


@pytest.mark.parametrize("p", [1, 4, 64])
def test_dft_direct_pi_layout_and_p_invariance(p):
    n = 1024
    x = rand_c64(n, seed=3)
    y = np.asarray(dft_direct_pi(x, p))
    nat = pi_layout_to_natural(y)
    assert rel_err(nat, np.fft.fft(x.astype(np.complex128))) < 1e-4
    base = np.asarray(dft_direct_pi(x, 1))
    assert np.allclose(y, base, atol=1e-5)


def test_pi_layout_matches_butterfly_models():
    """Same pi layout as the funnel/tube models — the whole verification
    stack (gather, golden, cross-backend) applies unchanged."""
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.models.pi_fft import pi_fft_pi_layout

    n, p = 512, 8
    x = rand_c64(n, seed=4)
    yr, yi = pi_fft_pi_layout(
        jnp.asarray(x.real), jnp.asarray(x.imag), p
    )
    butterfly = np.asarray(yr) + 1j * np.asarray(yi)
    einsum = np.asarray(dft_direct_pi(x, p))
    assert rel_err(einsum, butterfly) < 1e-4


def test_dft_direct_pi_planes_matches_complex():
    from cs87project_msolano2_tpu.models.direct_dft import dft_direct_pi_planes

    n, p = 512, 8
    x = rand_c64(n, seed=5)
    yr, yi = dft_direct_pi_planes(x.real, x.imag, p)
    ref = np.asarray(dft_direct_pi(x, p))
    got = np.asarray(yr) + 1j * np.asarray(yi)
    assert rel_err(got, ref.astype(np.complex128)) < 1e-4


def test_max_n_guard():
    with pytest.raises(ValueError):
        dft_matrix(MAX_N * 2)


def test_einsum_backend_golden():
    from cs87project_msolano2_tpu.backends.registry import get_backend
    from cs87project_msolano2_tpu.utils import verify

    res = get_backend("einsum").run(verify.golden_input(), 4)
    nat = verify.pi_layout_to_natural(res.out)
    # einsum accumulates differently; golden values are exact integers but
    # float32 matmul may not hit them bit-exactly -> tolerance check
    assert verify.max_abs_err(nat, verify.golden_expected()) < 1e-4
    # honest phase timers that compose (reference nesting semantics)
    assert res.funnel_ms > 0.0 and res.tube_ms > 0.0
    assert abs(res.funnel_ms + res.tube_ms - res.total_ms) < 1e-9


# --- the phased einsum model (funnel/tube as coefficient einsums) ------


@pytest.mark.parametrize("n,p", [(64, 8), (1024, 1), (4096, 16), (16384, 64)])
def test_phased_einsum_matches_butterfly(n, p):
    import jax
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.models.direct_dft import pi_dft_einsum_planes
    from cs87project_msolano2_tpu.models.pi_fft import pi_fft_pi_layout

    x = rand_c64(n, seed=6)
    xr = jnp.asarray(x.real.astype(np.float32))
    xi = jnp.asarray(x.imag.astype(np.float32))
    ar, ai = jax.jit(lambda a, b: pi_dft_einsum_planes(a, b, p))(xr, xi)
    br, bi = pi_fft_pi_layout(xr, xi, p)
    a = np.asarray(ar) + 1j * np.asarray(ai)
    b = np.asarray(br) + 1j * np.asarray(bi)
    assert rel_err(a, b.astype(np.complex128)) < 1e-4


def test_funnel_einsum_is_the_funnel():
    """The polyphase identity: the funnel IS a coefficient einsum."""
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.models.direct_dft import funnel_einsum_planes
    from cs87project_msolano2_tpu.models.pi_fft import funnel

    n, p = 2048, 16
    x = rand_c64(n, seed=7)
    xr = jnp.asarray(x.real.astype(np.float32))
    xi = jnp.asarray(x.imag.astype(np.float32))
    ar, ai = funnel_einsum_planes(xr, xi, p)
    br, bi = funnel(xr, xi, p)
    a = np.asarray(ar) + 1j * np.asarray(ai)
    b = np.asarray(br) + 1j * np.asarray(bi)
    assert rel_err(a, b.astype(np.complex128)) < 1e-5


def test_tube_einsum_scan_path_matches_dense():
    """Blockwise lax.scan generation == dense matrix application."""
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.models.direct_dft import tube_einsum_planes

    n, p = 4096, 4  # s = 1024
    x = rand_c64(n, seed=8)
    sr = jnp.asarray(x.real.astype(np.float32)).reshape(p, n // p)
    si = jnp.asarray(x.imag.astype(np.float32)).reshape(p, n // p)
    dr, di = tube_einsum_planes(sr, si, n, p, block=n // p)  # dense
    br, bi = tube_einsum_planes(sr, si, n, p, block=64)  # scan
    assert np.max(np.abs(np.asarray(dr) - np.asarray(br))) < 1e-3
    assert np.max(np.abs(np.asarray(di) - np.asarray(bi))) < 1e-3


def test_einsum_capacity_guard():
    from cs87project_msolano2_tpu.models.direct_dft import (
        COEF_MAX_ENTRIES,
        funnel_coeff_planes,
    )

    with pytest.raises(ValueError):
        funnel_coeff_planes(COEF_MAX_ENTRIES, 4)


def test_tube_hostblocked_matches_scan():
    """The host-driven blocked tube (the relay capacity-lift path,
    backends/jax_backend.py::einsum_tube_kblock) must equal the
    single-program scan tube row for row."""
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.models.direct_dft import (
        tube_einsum_planes,
        tube_einsum_planes_hostblocked,
    )

    n, p = 4096, 4  # s = 1024
    x = rand_c64(n, seed=9)
    sr = jnp.asarray(x.real.astype(np.float32)).reshape(p, n // p)
    si = jnp.asarray(x.imag.astype(np.float32)).reshape(p, n // p)
    ar, ai = tube_einsum_planes(sr, si, n, p)
    br, bi = tube_einsum_planes_hostblocked(sr, si, n, p, kblock=128)
    assert np.max(np.abs(np.asarray(ar) - np.asarray(br))) < 1e-3
    assert np.max(np.abs(np.asarray(ai) - np.asarray(bi))) < 1e-3


def test_hostblocked_full_pi_dft_vs_numpy():
    """funnel + host-blocked tube end-to-end against numpy's FFT (the
    shape the lifted einsum backend runs for s > EINSUM_TUBE_MAX_S)."""
    import jax
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.models.direct_dft import (
        funnel_einsum_planes,
        tube_einsum_block,
        tube_einsum_planes_hostblocked,
    )
    from cs87project_msolano2_tpu.ops.bits import bit_reverse_indices
    from functools import partial

    n, p = 4096, 2  # s = 2048
    kblock = 256
    x = rand_c64(n, seed=10)
    xr = jnp.asarray(x.real.astype(np.float32))
    xi = jnp.asarray(x.imag.astype(np.float32))
    fr, fi = funnel_einsum_planes(xr, xi, p)
    block_fn = jax.jit(partial(tube_einsum_block, n=n, p=p, kblock=kblock))
    tr, ti = tube_einsum_planes_hostblocked(fr, fi, n, p, kblock,
                                            block_fn=block_fn)
    y = (np.asarray(tr) + 1j * np.asarray(ti)).reshape(n)
    ref = np.fft.fft(x.astype(np.complex128))[bit_reverse_indices(n)]
    assert rel_err(y, ref) < 1e-5


def test_einsum_tube_kblock_policy():
    from cs87project_msolano2_tpu.backends.jax_backend import (
        EINSUM_TUBE_ABS_MAX_S,
        EINSUM_TUBE_MAX_PROGRAMS,
        EINSUM_TUBE_MAX_S,
        einsum_tube_kblock,
    )

    assert einsum_tube_kblock(EINSUM_TUBE_MAX_S) is None  # fits one program
    for s in (1 << 15, 1 << 16, 1 << 17):
        kb = einsum_tube_kblock(s)
        assert kb is not None and s % kb == 0
        assert kb * s <= EINSUM_TUBE_MAX_S ** 2  # per-program budget
        assert s // kb <= EINSUM_TUBE_MAX_PROGRAMS
    assert (1 << 17) == EINSUM_TUBE_ABS_MAX_S
