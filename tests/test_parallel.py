"""Multi-device tests on the 8-way virtual CPU mesh (SURVEY.md §4's
required multi-device path).  The crown jewel: the compiled sharded
pi-FFT must contain ZERO collectives — the machine-checked form of the
paper's no-communication thesis."""

import jax
import jax.numpy as jnp
import numpy as np

from cs87project_msolano2_tpu.parallel import (
    fft2_sharded,
    fft_batched_sharded,
    make_mesh,
    make_mesh2d,
    pi_fft_sharded,
    pi_fft_sharded_batched,
    poisson_solve_sharded,
)
from cs87project_msolano2_tpu.utils.verify import pi_layout_to_natural, rel_err

COLLECTIVE_HLO_OPS = ("all-to-all", "all-reduce", "all-gather",
                      "collective-permute", "reduce-scatter")


def rand_c64(shape, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)).astype(
        np.complex64
    )


def test_pi_fft_sharded_matches_numpy(devices8):
    n = 1 << 12
    mesh = make_mesh(8)
    x = rand_c64(n, seed=1)
    yr, yi = jax.jit(
        lambda a, b: pi_fft_sharded(a, b, mesh)
    )(jnp.real(x), jnp.imag(x))
    nat = pi_layout_to_natural(np.asarray(yr) + 1j * np.asarray(yi))
    assert rel_err(nat, np.fft.fft(x.astype(np.complex128))) < 1e-5


def test_pi_fft_sharded_is_collective_free(devices8):
    """No communication: the compiled HLO must contain no collectives."""
    n = 1 << 12
    mesh = make_mesh(8)
    xr = jnp.zeros(n, jnp.float32)
    hlo = (
        jax.jit(lambda a, b: pi_fft_sharded(a, b, mesh))
        .lower(xr, xr)
        .compile()
        .as_text()
    )
    found = [op for op in COLLECTIVE_HLO_OPS if op in hlo]
    assert not found, f"sharded pi-FFT compiled with collectives: {found}"


def test_pi_fft_sharded_batched_2d_mesh(devices8):
    b, n = 8, 1 << 10
    mesh = make_mesh2d(2, 4)
    x = rand_c64((b, n), seed=2)
    yr, yi = jax.jit(
        lambda a, c: pi_fft_sharded_batched(a, c, mesh)
    )(jnp.real(x), jnp.imag(x))
    nat = pi_layout_to_natural(np.asarray(yr) + 1j * np.asarray(yi))
    ref = np.fft.fft(x.astype(np.complex128), axis=-1)
    assert rel_err(nat, ref) < 1e-5


def test_fft_batched_sharded(devices8):
    mesh = make_mesh(8, axis="data")
    x = rand_c64((16, 512), seed=3)
    y = jax.jit(lambda v: fft_batched_sharded(v, mesh))(x)
    ref = np.fft.fft(x.astype(np.complex128), axis=-1)
    assert rel_err(np.asarray(y), ref) < 1e-5


def test_fft_batched_planes_inverse(devices8):
    """The inverse branch of the DP-batched path: forward then inverse
    over the mesh must round-trip, and the inverse alone must match
    numpy's ifft — both through the plan's conj-trick executor."""
    from cs87project_msolano2_tpu.parallel.batched import fft_batched_planes

    mesh = make_mesh(8, axis="data")
    x = rand_c64((16, 512), seed=7)
    xr = jnp.real(x).astype(jnp.float32)
    xi = jnp.imag(x).astype(jnp.float32)
    yr, yi = fft_batched_planes(xr, xi, mesh)
    br, bi = fft_batched_planes(yr, yi, mesh, inverse=True)
    back = np.asarray(br) + 1j * np.asarray(bi)
    assert rel_err(back, x.astype(np.complex128)) < 1e-5
    ir, ii = fft_batched_planes(xr, xi, mesh, inverse=True)
    ref = np.fft.ifft(x.astype(np.complex128), axis=-1)
    assert rel_err(np.asarray(ir) + 1j * np.asarray(ii), ref) < 1e-5


def test_fft_batched_planes_pi_layout(devices8):
    """natural=False (forward only) returns the kernel-native pi
    layout: per-row bit-reversed — undoing it per row must recover
    numpy's natural-order FFT."""
    from cs87project_msolano2_tpu.parallel.batched import fft_batched_planes

    mesh = make_mesh(8, axis="data")
    x = rand_c64((8, 256), seed=8)
    yr, yi = fft_batched_planes(jnp.real(x).astype(jnp.float32),
                                jnp.imag(x).astype(jnp.float32),
                                mesh, natural=False)
    got = np.asarray(yr) + 1j * np.asarray(yi)
    ref = np.fft.fft(x.astype(np.complex128), axis=-1)
    nat = np.stack([pi_layout_to_natural(row) for row in got])
    assert rel_err(nat, ref) < 1e-5
    # and it IS a permutation, not already natural order
    assert rel_err(got, ref) > 1e-3


def test_fft_batched_planes_per_shard_plan_key(devices8, monkeypatch):
    """The plan is fetched for the PER-SHARD shape (what each device
    actually transforms), with the layout following the natural/
    inverse/pi rules — the dispatch contract the module docstring
    promises."""
    from cs87project_msolano2_tpu.parallel import batched

    seen = []
    real_plan_for = batched.plans.plan_for

    def spy(shape, layout="natural", precision=None, domain="c2c"):
        seen.append((tuple(shape), layout, precision))
        return real_plan_for(shape, layout=layout, precision=precision,
                             domain=domain)

    monkeypatch.setattr(batched.plans, "plan_for", spy)
    mesh = make_mesh(8, axis="data")
    x = rand_c64((16, 512), seed=9)
    xr = jnp.real(x).astype(jnp.float32)
    xi = jnp.imag(x).astype(jnp.float32)
    batched.fft_batched_planes(xr, xi, mesh)                 # natural
    batched.fft_batched_planes(xr, xi, mesh, natural=False)  # pi
    batched.fft_batched_planes(xr, xi, mesh, inverse=True,
                               natural=False)  # inverse forces natural
    batched.fft_batched_planes(xr, xi, mesh, precision="fp32")
    assert seen == [
        ((2, 512), "natural", None),   # 16 rows over 8 shards
        ((2, 512), "pi", None),
        ((2, 512), "natural", None),
        ((2, 512), "natural", "fp32"),
    ]


def test_fft2_sharded(devices8):
    mesh = make_mesh(8)
    x = rand_c64((64, 256), seed=4)
    y = jax.jit(lambda v: fft2_sharded(v, mesh))(x)
    assert rel_err(np.asarray(y), np.fft.fft2(x.astype(np.complex128))) < 1e-5


def test_fft2_sharded_uses_all_to_all(devices8):
    """The 2-D transform is the config that genuinely needs ICI."""
    mesh = make_mesh(8)
    x = jnp.zeros((64, 256), jnp.complex64)
    hlo = (
        jax.jit(lambda v: fft2_sharded(v, mesh)).lower(x).compile().as_text()
    )
    assert "all-to-all" in hlo


def test_fft2_roundtrip(devices8):
    mesh = make_mesh(8)
    x = rand_c64((32, 128), seed=5)
    y = jax.jit(lambda v: fft2_sharded(v, mesh))(x)
    back = jax.jit(lambda v: fft2_sharded(v, mesh, inverse=True))(y)
    assert rel_err(np.asarray(back), x.astype(np.complex128)) < 1e-5


def test_poisson3d(devices8):
    """Solve lap(u) = f and check against the numpy spectral oracle."""
    n1, n2, n3 = 16, 16, 8
    mesh = make_mesh(8)
    rng = np.random.default_rng(6)
    u_true = rng.standard_normal((n1, n2, n3)).astype(np.float32)
    u_true -= u_true.mean()

    # f = lap(u_true), computed with an independent numpy spectral oracle
    k = lambda m: np.where(np.arange(m) > m // 2, np.arange(m) - m, np.arange(m))
    K1, K2, K3 = np.meshgrid(k(n1), k(n2), k(n3), indexing="ij")
    ksq = (K1**2 + K2**2 + K3**2).astype(np.float64)
    f = np.fft.ifftn(-ksq * np.fft.fftn(u_true)).real.astype(np.float32)

    u = jax.jit(lambda v: poisson_solve_sharded(v, mesh))(jnp.asarray(f))
    u = np.array(u)
    u -= u.mean()
    assert rel_err(u, u_true - u_true.mean()) < 1e-3


def test_sharded_harness_device_fns_correct():
    """The per-device timing harness (harness/run_sharded_experiments)
    times funnel_single + tube as the shard-local program; its output
    for device 0 must equal segment 0 of the full pi-FFT — otherwise the
    committed multi-chip dataset times the wrong computation."""
    import importlib.util
    import os
    import sys

    import jax.numpy as jnp

    from cs87project_msolano2_tpu.models.pi_fft import pi_fft_pi_layout

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "run_sharded_experiments",
        os.path.join(repo, "harness", "run_sharded_experiments.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    # the module sets JAX_PLATFORMS=cpu itself; under pytest that's
    # already the conftest environment
    sys.modules["run_sharded_experiments"] = mod
    spec.loader.exec_module(mod)

    n, p = 2048, 8
    rng = np.random.default_rng(3)
    xr = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    xi = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    funnel_f, tube_only, full = mod.device_fns(n, p)
    fr, fi = funnel_f(xr, xi)
    tr, ti = tube_only(fr, fi)
    rr, ri = pi_fft_pi_layout(xr, xi, p)
    seg_r = np.asarray(rr).reshape(p, n // p)[0]
    seg_i = np.asarray(ri).reshape(p, n // p)[0]
    assert np.max(np.abs(np.asarray(tr).ravel() - seg_r)) < 1e-3
    assert np.max(np.abs(np.asarray(ti).ravel() - seg_i)) < 1e-3
    # and the full composition agrees with the phase-by-phase path
    ar, ai = full(xr, xi)
    assert np.array_equal(np.asarray(ar), np.asarray(tr))
    assert np.array_equal(np.asarray(ai), np.asarray(ti))
