"""The live telemetry plane (docs/OBSERVABILITY.md, "The live
plane"): trace-context propagation (minted/adopted/NOOP, span trees,
fan-in links, flow-event export), the streaming /metrics + /healthz +
/slo endpoints, burn-rate SLO alerting wired into the degrade chain,
the shared nearest-rank percentile (property-tested against numpy),
Prometheus label escaping, and the dropped-event surfacing."""

import asyncio
import json

import numpy as np
import pytest

from cs87project_msolano2_tpu import obs
from cs87project_msolano2_tpu.obs import events, export, metrics
from cs87project_msolano2_tpu.obs import trace as trace_mod
from cs87project_msolano2_tpu.obs.slomon import (
    Objective,
    SloMonitor,
    load_objectives,
)
from cs87project_msolano2_tpu.utils.stats import (
    percentile_nearest_rank,
    percentile_or_none,
)


@pytest.fixture
def obs_run():
    rid = obs.enable()
    yield rid
    obs.disable()
    metrics.reset()


@pytest.fixture(autouse=True)
def _never_leak_enabled_state():
    yield
    if obs.enabled():
        obs.disable()
        metrics.reset()


# -------------------------------------------------------- trace context


def test_disabled_trace_mint_is_noop_singleton():
    """The no-op-span pattern extended to trace mint: disabled
    observability returns ONE shared instance, no allocation."""
    assert not obs.enabled()
    t1, t2 = trace_mod.mint(), trace_mod.ensure()
    assert t1 is t2 is trace_mod.NOOP_TRACE
    assert not t1.live
    assert trace_mod.adopt({"trace_id": "abc"}) is trace_mod.NOOP_TRACE
    assert t1.child() is trace_mod.NOOP_TRACE


def test_mint_child_and_adopt(obs_run):
    t = trace_mod.mint()
    assert t.live and t.sampled and t.parent_id is None
    c = t.child()
    assert c.trace_id == t.trace_id
    assert c.parent_id == t.span_id
    assert c.span_id != t.span_id
    # wire adoption: client trace id kept, client span becomes parent
    w = trace_mod.adopt({"trace_id": "feedface", "span_id": "c11e"})
    assert w.trace_id == "feedface" and w.parent_id == "c11e"
    assert trace_mod.adopt("feedface-c11e").parent_id == "c11e"
    # malformed wire fields mint instead of raising
    assert trace_mod.adopt({"bogus": 1}).live
    assert trace_mod.adopt("").live


def test_sample_rate_env(obs_run, monkeypatch):
    monkeypatch.setenv(trace_mod.SAMPLE_ENV, "0")
    assert not trace_mod.mint().sampled
    monkeypatch.setenv(trace_mod.SAMPLE_ENV, "1.0")
    assert trace_mod.mint().sampled
    monkeypatch.setenv(trace_mod.SAMPLE_ENV, "not-a-number")
    assert trace_mod.sample_rate() == 1.0  # warned fallback, not a kill


def test_contextvar_carry(obs_run):
    t = trace_mod.mint()
    assert trace_mod.current() is None
    with trace_mod.use(t):
        assert trace_mod.current() is t
        child = trace_mod.ensure()
        assert child.trace_id == t.trace_id
        assert child.parent_id == t.span_id
    assert trace_mod.current() is None


def test_request_span_records_sum_exactly(obs_run):
    t = trace_mod.mint()
    recs = trace_mod.request_span_records(
        t, label="1024:natural:split3", rid=7, t_submit=10.0,
        t_dequeue=10.002, t_exec=10.005, compute_s=0.003,
        t_done=10.0085, tags=["slo:jnp-fft"],
        marks=[("failover:vdev2", 10.004)])
    names = [r["name"] for r in recs]
    assert names == ["serve_request", "queue", "window", "compute",
                     "degrade:slo:jnp-fft", "failover:vdev2"]
    by = {r["name"]: r for r in recs}
    assert by["queue"]["dur_s"] == pytest.approx(0.002)
    assert by["window"]["dur_s"] == pytest.approx(0.003)
    assert by["compute"]["dur_s"] == pytest.approx(0.003)
    # every child parented on the root span id
    for r in recs[1:]:
        assert r["parent_sid"] == t.span_id
        assert r["trace"] == t.trace_id


def test_emit_respects_sampling_and_tail_upgrade(obs_run):
    unsampled = trace_mod.TraceContext("tid", "sid", sampled=False)
    recs = trace_mod.request_span_records(
        unsampled, label="l", rid=0, t_submit=0.0, t_dequeue=0.0,
        t_exec=0.0, compute_s=0.0, t_done=0.0)
    assert not trace_mod.emit_request_trace(unsampled, recs)
    assert events.span_snapshot() == []
    # the tail upgrade: degraded/failover/shed always emit
    assert trace_mod.emit_request_trace(unsampled, recs, forced=True)
    assert len(events.span_snapshot()) == len(recs)
    tree = trace_mod.wire_tree(unsampled, recs, emitted=True)
    assert tree["trace_id"] == "tid" and tree["spans"]
    bare = trace_mod.wire_tree(unsampled, recs, emitted=False)
    assert "spans" not in bare  # ids only on the unsampled path


# ------------------------------------------------- traced serving path


def _run(coro):
    return asyncio.run(coro)


def _dispatcher_burst(k=6, n=256, **cfg_kw):
    from cs87project_msolano2_tpu.serve.dispatcher import (
        Dispatcher,
        ServeConfig,
    )

    rng = np.random.default_rng(0)
    xr = rng.standard_normal(n).astype(np.float32)
    xi = rng.standard_normal(n).astype(np.float32)

    async def run():
        async with Dispatcher(ServeConfig(max_wait_ms=25.0,
                                          **cfg_kw)) as d:
            return d, await asyncio.gather(*[
                d.submit(xr, xi) for _ in range(k)])

    return _run(run())


def test_served_request_carries_span_tree(obs_run):
    _d, resps = _dispatcher_burst()
    r0 = resps[0]
    assert r0.trace and r0.trace["trace_id"]
    spans = r0.trace["spans"]
    names = [s["name"] for s in spans]
    assert names[:4] == ["serve_request", "queue", "window", "compute"]
    # the tree's phase children sum EXACTLY to the SLO row's total
    total = r0.queue_wait_ms + r0.compute_ms
    got = sum(s["dur_ms"] for s in spans
              if s["name"] in ("queue", "window", "compute"))
    assert got == pytest.approx(total, rel=0.05)
    root = r0.trace["span_id"]
    assert all(s.get("parent") == root for s in spans[1:])


def test_batch_span_links_equal_coalesced_count(obs_run):
    k = 6
    _d, _resps = _dispatcher_burst(k=k)
    batch_spans = [s for s in events.span_snapshot()
                   if s.get("name") == "serve_batch"]
    assert batch_spans
    linked = sum(len(s.get("links") or ()) for s in batch_spans)
    served = sum(s["cell"]["size"] for s in batch_spans)
    assert linked == served == k


def test_chrome_flow_events_from_links(obs_run):
    _d, _resps = _dispatcher_burst(k=4)
    doc = export.chrome_trace()
    flows = [e for e in doc["traceEvents"]
             if e.get("cat") == "pifft_flow"]
    assert flows, "links produced no flow events"
    starts = [e for e in flows if e["ph"] == "s"]
    finishes = [e for e in flows if e["ph"] == "f"]
    assert len(starts) == len(finishes) == 4
    assert all(e.get("bp") == "e" for e in finishes)
    by_id = {e["id"]: e for e in starts}
    for fin in finishes:  # arrows point forward in time
        assert by_id[fin["id"]]["ts"] <= fin["ts"]
    json.dumps(doc)  # the export stays loadable


def test_spans_from_events_passes_links_through(obs_run):
    with obs.span("fanin", links=["a1", "b2"], sid="s0"):
        pass
    recs = events.snapshot()
    spans = export.spans_from_events(recs)
    target = [s for s in spans if s.get("name") == "fanin"]
    assert target and target[0]["links"] == ["a1", "b2"]
    assert target[0]["sid"] == "s0"


def test_sampled_out_requests_emit_no_span_events(obs_run,
                                                 monkeypatch):
    monkeypatch.setenv(trace_mod.SAMPLE_ENV, "0")
    _d, resps = _dispatcher_burst(k=3)
    # ids still ride the response; the tree and the events do not
    assert all(r.trace and "spans" not in r.trace for r in resps)
    assert not [s for s in events.span_snapshot()
                if s.get("name") == "serve_request"]


def test_wire_trace_round_trip(obs_run):
    from cs87project_msolano2_tpu.serve.dispatcher import (
        Dispatcher,
        ServeConfig,
    )
    from cs87project_msolano2_tpu.serve.protocol import (
        handle_connection,
        request_over_socket,
    )

    rng = np.random.default_rng(1)
    xr = rng.standard_normal(256).astype(np.float32)

    async def run():
        async with Dispatcher(ServeConfig()) as d:
            server = await asyncio.start_server(
                lambda r, w: handle_connection(d, r, w),
                "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            try:
                minted = await request_over_socket(
                    "127.0.0.1", port, xr, np.zeros_like(xr),
                    domain="r2c")
                supplied = await request_over_socket(
                    "127.0.0.1", port, xr, np.zeros_like(xr),
                    domain="r2c",
                    trace={"trace_id": "feedface", "span_id": "c11e"})
            finally:
                server.close()
                await server.wait_closed()
            return minted, supplied

    minted, supplied = _run(run())
    assert minted["ok"] and minted["trace"]["trace_id"]
    assert supplied["trace"]["trace_id"] == "feedface"
    # the server-side root is parented on the client's span
    roots = [s for s in events.span_snapshot()
             if s.get("trace") == "feedface"
             and s.get("name") == "serve_request"]
    assert roots and roots[0]["parent_sid"] == "c11e"


def test_mesh_failover_span_under_same_trace(obs_run):
    from cs87project_msolano2_tpu.resilience.inject import inject
    from cs87project_msolano2_tpu.serve.loadgen import _group_for
    from cs87project_msolano2_tpu.serve.mesh import (
        MeshConfig,
        MeshDispatcher,
    )
    from cs87project_msolano2_tpu.serve.shapes import ShapeSpec

    rng = np.random.default_rng(2)
    specs = [ShapeSpec(n=256)]
    xr = rng.standard_normal(256).astype(np.float32)
    xi = rng.standard_normal(256).astype(np.float32)

    async def run():
        async with MeshDispatcher(MeshConfig(devices=3),
                                  specs) as mesh:
            await mesh.submit(xr, xi)  # prime
            victim = mesh.router.route(_group_for(specs[0]),
                                       record=False)
            with inject(victim.site, "permanent", count=1):
                resp = await mesh.submit(xr, xi)
            return victim.id, resp

    victim_id, resp = _run(run())
    hop = f"failover:{victim_id}"
    assert hop in resp.degrade
    assert resp.trace and resp.trace["spans"], "tail upgrade must emit"
    assert any(s["name"] == hop for s in resp.trace["spans"])
    # the hop span rides the request's OWN trace in the emitted stream
    recs = [s for s in events.span_snapshot()
            if s.get("trace") == resp.trace["trace_id"]]
    assert any(s.get("name") == hop for s in recs)


def test_shed_request_leaves_trace(obs_run):
    from cs87project_msolano2_tpu.serve.dispatcher import (
        Dispatcher,
        QueueFull,
        ServeConfig,
    )

    rng = np.random.default_rng(3)
    xr = rng.standard_normal(256).astype(np.float32)
    xi = rng.standard_normal(256).astype(np.float32)

    async def run():
        # depth 1: the submits all admit before the worker first runs
        # (task scheduling order), so everything past the first sheds
        async with Dispatcher(ServeConfig(queue_depth=1,
                                          max_wait_ms=1.0)) as d:
            tasks = [asyncio.ensure_future(d.submit(xr, xi))
                     for _ in range(6)]
            results = await asyncio.gather(*tasks,
                                           return_exceptions=True)
            return sum(1 for r in results
                       if isinstance(r, QueueFull))

    shed = _run(run())
    assert shed > 0
    sheds = [s for s in events.span_snapshot()
             if s.get("name") == "serve_request"
             and (s.get("args") or {}).get("shed")]
    assert sheds and sheds[0].get("error") == "queue_full"


# ------------------------------------------------------- live endpoints


def test_telemetry_endpoints_live(obs_run):
    from cs87project_msolano2_tpu.obs.http import (
        TelemetryServer,
        fetch_json,
        fetch_text,
    )
    from cs87project_msolano2_tpu.serve.dispatcher import (
        Dispatcher,
        ServeConfig,
    )

    rng = np.random.default_rng(0)
    xr = rng.standard_normal(256).astype(np.float32)
    xi = rng.standard_normal(256).astype(np.float32)

    async def run():
        async with Dispatcher(ServeConfig(max_wait_ms=25.0)) as d:
            await asyncio.gather(*[d.submit(xr, xi)
                                   for _ in range(4)])
            server = TelemetryServer(d).start()
            loop = asyncio.get_running_loop()
            try:
                # fetched WHILE the dispatcher is open and serving —
                # the live-plane contract, not a post-mortem
                prom = await loop.run_in_executor(
                    None, fetch_text, server.url("/metrics"))
                health = await loop.run_in_executor(
                    None, fetch_json, server.url("/healthz"))
                slo = await loop.run_in_executor(
                    None, fetch_json, server.url("/slo"))
                import urllib.error

                with pytest.raises(urllib.error.HTTPError) as exc:
                    await loop.run_in_executor(
                        None, fetch_json, server.url("/nope"))
                assert exc.value.code == 404
            finally:
                server.stop()
            return d, prom, health, slo

    d, prom, health, slo = _run(run())
    assert "# TYPE pifft_serve_requests_total counter" in prom
    assert health["ok"] and "queues" in health and "run" in health
    assert slo["window_s"] == d.stats.window_s
    row = slo["rows"]["256:natural:split3"]
    assert row["requests"] == 4
    assert row["total_p99_ms"] is not None


def test_healthz_503_when_all_devices_dead(obs_run):
    from cs87project_msolano2_tpu.obs.http import TelemetryServer
    from cs87project_msolano2_tpu.serve.mesh import (
        MeshConfig,
        MeshDispatcher,
    )

    mesh = MeshDispatcher(MeshConfig(devices=2))
    for dev in mesh.devices:
        dev.state = "dead"
    server = TelemetryServer(mesh).start()
    try:
        import urllib.error
        import urllib.request

        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(server.url("/healthz"), timeout=5)
        assert exc.value.code == 503
        doc = json.loads(exc.value.read().decode())
        assert doc["ok"] is False and doc["devices_alive"] == 0
    finally:
        server.stop()


def test_format_top_renders(obs_run):
    from cs87project_msolano2_tpu.obs.http import format_top

    frame = format_top(
        {"window_s": 60.0,
         "rows": {"1024:natural:split3": {
             "requests": 3, "degraded": 1, "queue_p99_ms": 1.0,
             "compute_p99_ms": 2.0, "total_p50_ms": 2.5,
             "total_p99_ms": 3.0}}},
        {"ok": True, "uptime_s": 12.0, "queued": 0,
         "devices": [{"state": "healthy"}],
         "devices_alive": 1})
    assert "SERVING" in frame and "1024:natural:split3" in frame
    empty = format_top({"rows": {}}, {"ok": False})
    assert "NOT SERVING" in empty


def test_sliding_window_ages_out(obs_run, monkeypatch):
    from cs87project_msolano2_tpu.serve import slo as slo_mod

    stats = slo_mod.LatencyStats(window_s=100.0)
    now = {"t": 1000.0}
    monkeypatch.setattr(slo_mod, "clock", lambda: now["t"])
    stats.record("a", 0.001, 0.002)
    stats.record("a", 0.003, 0.004, device="vdev1")
    rows = stats.window_summary()
    assert rows["a"]["requests"] == 1
    assert rows["a@vdev1"]["requests"] == 1  # device-keyed row
    now["t"] += 200.0  # the window slides past both samples
    rows = stats.window_summary()
    assert rows["a"]["requests"] == 0
    assert rows["a"]["total_p99_ms"] is None  # stable schema, nulled
    # the cumulative end-of-run summary is untouched by aging
    assert stats.summary()["a"]["requests"] == 2


# ---------------------------------------------------- burn-rate alerts


def test_objective_validation_and_load(tmp_path):
    with pytest.raises(ValueError):
        Objective("x", -1.0)
    with pytest.raises(ValueError):
        Objective("x", 10.0, error_budget=0.0)
    path = tmp_path / "slo.json"
    path.write_text(json.dumps({
        "windows": [2, 10],
        "objectives": [{"name": "conv", "match": "conv",
                        "p99_target_ms": 40, "error_budget": 0.02}]}))
    objectives, windows = load_objectives(str(path))
    assert windows == (2.0, 10.0)
    assert objectives[0].applies("conv", "whatever")
    assert not objectives[0].applies("fft", "other")
    bad = tmp_path / "bad.json"
    bad.write_text("{\"objectives\": []}")
    with pytest.raises(ValueError):
        load_objectives(str(bad))
    # duplicate names would silently merge their sample deques
    with pytest.raises(ValueError, match="duplicate"):
        SloMonitor([Objective("dup", 10.0), Objective("dup", 20.0)])


def test_forced_level_refreshes_across_idle_gap(obs_run):
    """A firing level must not outlive the burn just because no batch
    delivered during the idle gap: the admission-path read refreshes
    a stale evaluation."""
    mon = SloMonitor([Objective("o", 20.0, 0.05)], windows=(10, 30))
    t0 = 1000.0
    for i in range(6):
        mon.observe("fft", "l", 100.0, t=t0 + i)
    mon.evaluate(t=t0 + 6)
    assert mon.forced_level(t=t0 + 6) == "jnp-fft"
    # ... minutes of silence: the stale level must clear on read
    assert mon.forced_level(t=t0 + 600) is None
    assert not mon.alerting()["o"]


def test_sample_rate_parses_once_per_value(obs_run, monkeypatch,
                                           capsys):
    monkeypatch.setenv(trace_mod.SAMPLE_ENV, "bogus")
    assert trace_mod.sample_rate() == 1.0
    for _ in range(5):
        trace_mod.mint()
    # ONE warn per distinct malformed value, not one per mint
    assert capsys.readouterr().err.count("is not a number") == 1


def test_burn_rate_fires_and_resolves(obs_run):
    mon = SloMonitor([Objective("o", 20.0, 0.05)],
                     windows=(10.0, 30.0))
    t0 = 1000.0
    for i in range(6):
        mon.observe("fft", "l", 100.0, t=t0 + i)
    mon.evaluate(t=t0 + 6)
    assert mon.alerting()["o"]
    # burn 20 > rung threshold (t pins the synthetic clock domain)
    assert mon.forced_level(t=t0 + 6) == "jnp-fft"
    assert metrics.counter_value("pifft_slo_alerts_total",
                                 objective="o", state="firing") == 1
    # gauges live on every evaluation
    snap = metrics.snapshot()["gauges"]
    assert any(k.startswith("pifft_slo_burn_rate") for k in snap)
    # the burn drains as the windows slide
    for i in range(8):
        mon.observe("fft", "l", 1.0, t=t0 + 40 + i)
    mon.evaluate(t=t0 + 48)
    assert not mon.alerting()["o"]
    assert mon.forced_level(t=t0 + 48) is None
    # a drained window publishes burn 0, never its crisis reading
    gauges = metrics.snapshot()["gauges"]
    burn_vals = [v for k, v in gauges.items()
                 if k.startswith("pifft_slo_burn_rate")]
    assert burn_vals and all(v == 0.0 for v in burn_vals), gauges
    alerts = [e for e in obs.snapshot() if e.get("kind") == "slo_alert"]
    assert [e["payload"]["state"] for e in alerts] == ["firing",
                                                      "resolved"]
    assert not [p for e in alerts for p in events.validate_event(e)]


def test_too_few_samples_never_alert(obs_run):
    mon = SloMonitor([Objective("o", 20.0, 0.05)], windows=(10, 30))
    mon.observe("fft", "l", 999.0, t=1.0)
    mon.evaluate(t=1.5)
    assert not mon.alerting()["o"]  # 1 sample < min_samples


def test_slo_demotion_tags_responses(obs_run):
    from cs87project_msolano2_tpu.serve.dispatcher import (
        Dispatcher,
        ServeConfig,
    )

    mon = SloMonitor([Objective("o", 0.0001, 0.01)],
                     windows=(30.0, 60.0))
    rng = np.random.default_rng(0)
    xr = rng.standard_normal(256).astype(np.float32)
    xi = rng.standard_normal(256).astype(np.float32)

    async def run():
        # SEQUENTIAL submits: the first batches prime the monitor
        # (every request blows a 0.1us target), later admissions see
        # the forced level
        async with Dispatcher(ServeConfig(max_wait_ms=0.5,
                                          slo_objectives=mon)) as d:
            out = []
            for _ in range(8):
                out.append(await d.submit(xr, xi))
            return out

    resps = _run(run())
    tagged = [r for r in resps
              if any(str(t).startswith("slo:") for t in r.degrade)]
    assert tagged, [r.degrade for r in resps]
    assert all(r.degraded for r in tagged)
    # alert event emitted and schema-valid
    alerts = [e for e in obs.snapshot() if e.get("kind") == "slo_alert"]
    assert alerts
    levels = [e for e in obs.snapshot()
              if e.get("kind") == "serve_degrade"
              and str((e.get("payload") or {}).get("level", ""))
              .startswith("slo:")]
    assert levels, "admission never recorded the slo level"


def test_dispatcher_builds_monitor_from_config_path(tmp_path):
    from cs87project_msolano2_tpu.serve.dispatcher import (
        Dispatcher,
        ServeConfig,
    )

    path = tmp_path / "slo.json"
    path.write_text(json.dumps([{"name": "all", "p99_target_ms": 50}]))
    d = Dispatcher(ServeConfig(slo_objectives=str(path)))
    assert d.slomon is not None
    assert d.slomon.objectives[0].name == "all"
    assert Dispatcher(ServeConfig()).slomon is None


# ------------------------------------------- shared percentile helper


@pytest.mark.parametrize("q", [0, 1, 25, 50, 75, 90, 99, 99.9, 100])
def test_percentile_matches_numpy_inverted_cdf(q):
    """Property: the shared helper == numpy's nearest-rank mode over
    random populations (the satellite's unification contract)."""
    rng = np.random.default_rng(42)
    for size in (1, 2, 3, 7, 100, 1001):
        values = rng.standard_normal(size).tolist()
        got = percentile_nearest_rank(values, q)
        want = float(np.percentile(values, q, method="inverted_cdf"))
        assert got == pytest.approx(want), (q, size)


def test_percentile_edges():
    assert percentile_nearest_rank([5.0], 99) == 5.0
    assert percentile_nearest_rank([1, 2, 3], 0) == 1
    assert percentile_nearest_rank([1, 2, 3], 100) == 3
    with pytest.raises(ValueError):
        percentile_nearest_rank([], 50)
    with pytest.raises(ValueError):
        percentile_nearest_rank([1], 101)
    assert percentile_or_none([], 99) is None
    assert percentile_or_none([2.0], 50) == 2.0


def test_slo_and_loadgen_share_the_one_implementation():
    from cs87project_msolano2_tpu.serve import loadgen, slo
    from cs87project_msolano2_tpu.utils import stats

    assert slo.percentile is stats.percentile_nearest_rank
    assert slo.percentile_or_none is stats.percentile_or_none
    assert loadgen.percentile_or_none is stats.percentile_or_none


# --------------------------------------------- Prometheus text edges


def test_prometheus_label_values_escaped(obs_run):
    metrics.inc("pifft_test_total",
                shape='with"quote', note="back\\slash\nnewline")
    text = export.prometheus_text()
    line = [ln for ln in text.splitlines()
            if ln.startswith("pifft_test_total")][0]
    assert 'shape="with\\"quote"' in line
    assert "back\\\\slash\\nnewline" in line
    assert "\n" not in line  # the raw newline never splits the series


def test_histogram_buckets_cumulative_and_inf_terminated(obs_run):
    for v in (0.003, 0.03, 0.3, 3.0, 30.0):
        metrics.observe("pifft_test_seconds", v, shape="s")
    text = export.prometheus_text()
    buckets = [ln for ln in text.splitlines()
               if ln.startswith("pifft_test_seconds_bucket")]
    counts = [float(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts), "buckets must be cumulative"
    assert 'le="+Inf"' in buckets[-1]
    assert counts[-1] == 5.0  # +Inf == count
    assert "pifft_test_seconds_sum" in text
    assert "pifft_test_seconds_count" in text
    # every bucket line keeps its base labels beside le
    assert all('shape="s"' in ln for ln in buckets)


# ----------------------------------------------- dropped-event surfacing


def test_dropped_events_counted_warned_and_summarized(capsys):
    obs.enable(buffer_max=8)
    try:
        for i in range(20):
            obs.emit("spam", i=i)
        assert events.dropped() > 0
        assert metrics.counter_value("pifft_obs_dropped_total") \
            == events.dropped()
        err = capsys.readouterr().err
        assert err.count("obs buffer overflowed") == 1  # warn ONCE
        obs.emit("metrics", snapshot=metrics.snapshot())
        summary = export.summarize(events.snapshot())
        assert summary["dropped_events"] == events.dropped()
        assert "DROPPED" in export.format_summary(summary)
    finally:
        obs.disable()
        metrics.reset()


def test_no_drop_no_warning(obs_run, capsys):
    obs.emit("fine")
    summary = export.summarize(events.snapshot())
    assert summary["dropped_events"] == 0
    assert "DROPPED" not in export.format_summary(summary)
    assert "overflowed" not in capsys.readouterr().err


# --------------------------------------------------- tail attribution


def test_tail_attribution_names_the_owner(obs_run):
    from cs87project_msolano2_tpu.analyze.loader import (
        tail_attribution,
    )

    # hand-built trees: 9 fast compute-bound requests, one queue-bound
    # outlier — the p99 owner must be the outlier's queue phase
    def tree(rid, queue_s, compute_s):
        t = trace_mod.mint()
        recs = trace_mod.request_span_records(
            t, label="512:natural:split3", rid=rid, t_submit=0.0,
            t_dequeue=queue_s, t_exec=queue_s,
            compute_s=compute_s, t_done=queue_s + compute_s)
        trace_mod.emit_request_trace(t, recs)

    for rid in range(9):
        tree(rid, queue_s=0.001, compute_s=0.004)
    tree(9, queue_s=0.050, compute_s=0.004)
    table = tail_attribution(obs.snapshot())
    row = table["512:natural:split3"]
    assert row["requests"] == 10
    assert row["p99_owner"] == "queue"
    assert row["p99_queue_share"] > 0.8
    assert row["p50_ms"] < row["p99_ms"]
    shares = (row["p99_queue_share"] + row["p99_window_share"]
              + row["p99_compute_share"])
    assert shares == pytest.approx(1.0, abs=0.01)


def test_tail_attribution_skips_incomplete_trees(obs_run):
    from cs87project_msolano2_tpu.analyze.loader import (
        tail_attribution,
    )

    t = trace_mod.mint()
    events.record_span({"name": "serve_request", "ts_s": 0.0,
                        "dur_s": 1.0, "tid": 1, "sid": t.span_id,
                        "trace": t.trace_id,
                        "args": {"shape": "x"}})  # no children
    assert tail_attribution(obs.snapshot()) == {}


# ----------------------------------------------------- check-rule scope


def test_obs_http_in_pif107_and_pif112_scope():
    """The live plane sits inside the serve concurrency rules' scope
    (the satellite's wiring): both configs name obs/http.py."""
    import fnmatch

    from cs87project_msolano2_tpu.check.rules import (
        BlockingCallInAsyncServePath,
    )
    from cs87project_msolano2_tpu.check.rules_flow import (
        UnguardedSharedStateWrite,
    )

    path = "/repo/cs87project_msolano2_tpu/obs/http.py"
    for rule in (BlockingCallInAsyncServePath,
                 UnguardedSharedStateWrite):
        pats = rule.default_config["paths"]
        assert any(fnmatch.fnmatch(path, p) for p in pats), \
            (rule.id, pats)


def test_pif107_flags_async_blocking_in_obs_http(tmp_path):
    """A constructed async time.sleep in an obs/http.py path is a
    finding — the scope has teeth, not just a glob entry."""
    from cs87project_msolano2_tpu.check.engine import check_paths

    target = tmp_path / "obs" / "http.py"
    target.parent.mkdir()
    target.write_text(
        "import time\n\n\n"
        "async def handler():\n"
        "    time.sleep(1)\n")
    findings = check_paths([str(target)], rules=["PIF107"])
    assert any(f.rule == "PIF107" for f in findings), findings
    # the shipped module itself stays CLEAN under the widened scope
    import cs87project_msolano2_tpu.obs.http as http_mod

    assert not check_paths([http_mod.__file__], rules=["PIF107"])


def test_slomon_describe_round_trips_json():
    mon = SloMonitor([Objective("o", 20.0)], windows=(5, 60))
    json.dumps(mon.describe())  # the /healthz surface stays JSON-safe


def test_obs_top_once_renders_live_server(obs_run, capsys):
    """`pifft obs top --once` against a live telemetry plane prints
    one frame and exits 0; with no server it fails structurally."""
    from cs87project_msolano2_tpu.cli import main as cli_main
    from cs87project_msolano2_tpu.obs.http import TelemetryServer

    server = TelemetryServer(None).start()
    try:
        rc = cli_main(["obs", "top", "--once", "--url", server.url()])
    finally:
        server.stop()
    assert rc == 0
    out = capsys.readouterr().out
    assert "pifft live telemetry" in out
    rc = cli_main(["obs", "top", "--once",
                   "--url", "http://127.0.0.1:9"])  # nothing there
    assert rc == 1
    assert "no telemetry plane" in capsys.readouterr().err


def test_telemetry_server_stops_cleanly(obs_run):
    from cs87project_msolano2_tpu.obs.http import TelemetryServer

    server = TelemetryServer(None).start()
    port = server.port
    server.stop()
    # the port is released: a second server can bind it immediately
    import socket

    s = socket.socket()
    try:
        s.bind(("127.0.0.1", port))
    finally:
        s.close()
