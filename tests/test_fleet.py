"""Fleet-loop unit tests (docs/FLEET.md): live-vs-baseline Mann-Whitney
detectors, the drift scanner, the canary racer's promote/abort/rollback
contract (byte-identical store restore, journaled epochs, demotion
discipline), the decayed arrival model's persistence semantics, the
``shifted`` load process, plan-cache store locking, slomon hot-reload,
and the schema'd fleet event kinds.  The end-to-end loop (drift →
race → promote → recover → rollback → prewarm across a mesh restart)
is the ``fleet-smoke`` CI gate; these tests pin the pieces."""

import json
import os
import threading
import time

import numpy as np
import pytest

from cs87project_msolano2_tpu import obs, plans
from cs87project_msolano2_tpu.analyze import regress
from cs87project_msolano2_tpu.fleet import (
    ArrivalModel,
    CanaryController,
    DriftDetector,
    TrafficMirror,
    model_path,
)
from cs87project_msolano2_tpu.obs import events, metrics
from cs87project_msolano2_tpu.plans import cache as plan_cache
from cs87project_msolano2_tpu.plans.core import Plan
from cs87project_msolano2_tpu.resilience.inject import inject
from cs87project_msolano2_tpu.resilience.journal import Journal
from cs87project_msolano2_tpu.serve import loadgen
from cs87project_msolano2_tpu.serve.batcher import GroupKey
from cs87project_msolano2_tpu.serve.mesh import MeshDevice
from cs87project_msolano2_tpu.serve.router import (
    NoDeviceAvailable,
    Router,
)


@pytest.fixture
def obs_run():
    rid = obs.enable()
    yield rid
    obs.disable()
    metrics.reset()


@pytest.fixture(autouse=True)
def _fresh_plan_memory():
    plan_cache.clear(memory=True, disk=False)
    yield
    plan_cache.clear(memory=True, disk=False)


# --------------------------------------------------------- detectors


def test_live_regressed_flags_only_real_shifts():
    base = [1.0 + 0.01 * i for i in range(40)]
    slow = [2.0 + 0.01 * i for i in range(40)]
    v = regress.live_regressed(base, slow)
    assert v.significant and v.test == "mann-whitney"
    assert v.med_change > 0.5 and v.p_value < 0.05
    same = regress.live_regressed(base, list(base))
    assert not same.significant
    # an IMPROVEMENT is not a regression, however significant
    fast = [0.1] * 40
    assert not regress.live_regressed(base, fast).significant


def test_live_detectors_refuse_tiny_populations():
    v = regress.live_regressed([1.0] * 3, [9.0] * 40)
    assert not v.significant and v.test == "insufficient"
    v = regress.live_improved([9.0] * 40, [1.0] * 4)
    assert not v.significant and v.test == "insufficient"
    assert v.samples == (40, 4)


def test_live_improved_requires_min_change():
    live = [1.0 + 0.001 * i for i in range(40)]
    better = [0.5] * 20
    assert regress.live_improved(live, better).significant
    # statistically distinguishable but practically identical
    barely = [v - 0.02 for v in live[:20]]
    assert not regress.live_improved(
        live, barely, min_change=0.25).significant


class _StubStats:
    def __init__(self, totals):
        self.totals = totals

    def window_totals(self, window_s=None):
        return self.totals


def test_drift_detector_merges_devices_and_emits(obs_run):
    stats = _StubStats({
        "256:natural:split3@vdev0": [0.030] * 10,
        "256:natural:split3@vdev1": [0.032] * 10,
        "512:natural:split3@vdev0": [0.002] * 10,
    })
    det = DriftDetector(stats, min_samples=8)
    det.set_baseline("256:natural:split3", [2.0] * 20)   # ms
    det.set_baseline("512:natural:split3", [2.0] * 20)
    findings = {f.label: f for f in det.scan()}
    f = findings["256:natural:split3"]
    assert f.drifted and len(f.live_ms) == 20   # both devices merged
    assert f.live_p99_ms > f.baseline_p99_ms
    assert not findings["512:natural:split3"].drifted
    drift_events = [r for r in events.snapshot()
                    if r["kind"] == "fleet_drift"]
    assert len(drift_events) == 1
    assert not events.validate_event(drift_events[0])
    assert metrics.counter_value("pifft_fleet_drift_total",
                                 shape="256:natural:split3") == 1.0


def test_drift_detector_baseline_capture_respects_min_samples():
    stats = _StubStats({"a": [0.001] * 20, "b": [0.001] * 3})
    det = DriftDetector(stats, min_samples=8)
    assert det.capture_baseline() == ["a"]
    assert det.baselines() == ["a"]
    # too few live samples: the scan stays silent rather than running
    # an anticonservative MW on a half-empty window
    stats.totals = {"a": [0.5] * 4}
    assert det.scan() == []


# ------------------------------------------------------------ canary


def _fast_timer(ms=1.0):
    def timer(fn, key):
        return ms
    return timer


def test_canary_promotes_on_verdict_and_journals_epoch(
        tmp_path, monkeypatch, obs_run):
    monkeypatch.setenv("PIFFT_PLAN_CACHE", str(tmp_path / "cache"))
    journal = Journal(str(tmp_path / "journal.jsonl"))
    ctl = CanaryController(journal=journal)
    key = plans.make_key(256)
    live_ms = [30.0 + 0.1 * i for i in range(40)]
    out = ctl.race(key, live_ms, timer=_fast_timer(),
                   candidate_samples=[1.0 + 0.01 * i
                                      for i in range(8)])
    assert out.promoted and not out.rolled_back
    assert out.epoch == 1 and out.verdict.significant
    store = plan_cache.store_path(key.device_kind)
    with open(store, encoding="utf-8") as fh:
        assert key.token() in json.load(fh)["plans"]
    cells = journal.load()
    assert f"promote:{key.token()}:e1" in cells
    assert f"promoted:{key.token()}:e1" in cells
    kinds = [r["kind"] for r in events.snapshot()]
    assert "fleet_canary" in kinds and "fleet_promote" in kinds
    for rec in events.snapshot():
        assert not events.validate_event(rec), rec


def test_canary_rejects_insignificant_candidate(
        tmp_path, monkeypatch, obs_run):
    monkeypatch.setenv("PIFFT_PLAN_CACHE", str(tmp_path / "cache"))
    ctl = CanaryController()
    key = plans.make_key(256)
    live_ms = [1.0 + 0.01 * i for i in range(40)]
    # the candidate population straddles the live median: no verdict
    out = ctl.race(key, live_ms, timer=_fast_timer(),
                   candidate_samples=[1.16 + 0.01 * i
                                      for i in range(8)])
    assert not out.promoted and not out.rolled_back
    assert out.epoch is None
    store = plan_cache.store_path(key.device_kind)
    assert store is None or not os.path.exists(store)
    # the unpromoted shadow winner must not serve from the LRU
    assert plans.get_plan(key).source != "tuned"


def test_canary_site_fault_aborts_before_any_write(
        tmp_path, monkeypatch, obs_run):
    monkeypatch.setenv("PIFFT_PLAN_CACHE", str(tmp_path / "cache"))
    journal = Journal(str(tmp_path / "journal.jsonl"))
    ctl = CanaryController(journal=journal)
    key = plans.make_key(256)
    with inject("canary", "transient", count=1):
        out = ctl.race(key, [30.0] * 40, timer=_fast_timer(),
                       candidate_samples=[1.0] * 8)
    assert not out.promoted and not out.rolled_back
    assert "aborted" in out.reason
    assert journal.load() == {}
    store = plan_cache.store_path(key.device_kind)
    assert store is None or not os.path.exists(store)
    assert metrics.counter_value("pifft_fleet_rollback_total") == 0.0


def test_promote_fault_rolls_back_byte_identical(
        tmp_path, monkeypatch, obs_run):
    monkeypatch.setenv("PIFFT_PLAN_CACHE", str(tmp_path / "cache"))
    journal = Journal(str(tmp_path / "journal.jsonl"))
    key = plans.make_key(256)
    # a pre-existing store entry (another key) must survive untouched
    other = plans.make_key(512)
    plan_cache.store(Plan(key=other, variant="rql", params={},
                          source="tuned", ms=1.0))
    store = plan_cache.store_path(key.device_kind)
    with open(store, "rb") as fh:
        pre = fh.read()

    ctl = CanaryController(journal=journal)
    live_ms = [30.0 + 0.1 * i for i in range(40)]
    with inject("promote", "permanent", count=1):
        out = ctl.race(key, live_ms, timer=_fast_timer(),
                       candidate_samples=[1.0] * 8)
    assert out.rolled_back and not out.promoted
    with open(store, "rb") as fh:
        assert fh.read() == pre
    assert f"rollback:{key.token()}:e1" in journal.load()
    assert metrics.counter_value("pifft_fleet_rollback_total") == 1.0
    rb = [r for r in events.snapshot()
          if r["kind"] == "fleet_rollback"]
    assert len(rb) == 1 and not events.validate_event(rb[0])
    payload = rb[0]["payload"]
    assert payload["kind"] == "permanent"
    assert payload["to"] == out.prior_variant
    # demotion discipline on the demoted candidate plan
    assert out.plan.degraded and out.plan.demotions[-1]["kind"] == \
        "permanent"


def test_traffic_mirror_copies_and_bounds():
    mirror = TrafficMirror(per_group=2)
    group = GroupKey(n=8)
    xr = np.ones(8, dtype=np.float32)
    mirror.observe(group, xr, None)
    xr[0] = 99.0   # the mirror must hold a COPY
    mirror.observe(group, np.full(8, 2.0), np.full(8, 3.0))
    mirror.observe(group, np.full(8, 4.0), np.full(8, 5.0))
    planes = mirror.planes(group)
    assert len(planes) == 2   # newest two
    assert planes[0][0][0] == 2.0 and planes[1][0][0] == 4.0
    assert mirror.planes(GroupKey(n=16)) == []


def test_router_canary_designation_excludes_device():
    devices = [MeshDevice(i) for i in range(3)]
    router = Router(devices)
    group = GroupKey(n=8)
    router.set_canary("vdev2")
    assert [d.id for d in router.candidates()] == ["vdev0", "vdev1"]
    device, _why, _warmth, _load = router.choose(group)
    assert device.id != "vdev2"
    router.set_canary(None)
    assert len(router.candidates()) == 3
    for d in devices:
        d.state = "dead"
    with pytest.raises(NoDeviceAvailable):
        router.choose(group)


# ----------------------------------------------------- arrival model


def test_arrival_model_decay_and_hot_order():
    model = ArrivalModel(half_life_s=10.0, min_weight=0.5)
    hot_group = GroupKey(n=256)
    cold_group = GroupKey(n=512)
    for _ in range(8):
        model.observe(hot_group, now=100.0)
    model.observe(cold_group, now=100.0)
    hot = model.hot(now=100.0)
    assert [k[0] for _w, k in hot] == [256, 512]
    # two half-lives later the cold shape decays under the floor
    # (0.25 < min_weight) while the hot one is still worth a compile
    hot = model.hot(now=120.0)
    assert [k[0] for _w, k in hot] == [256]
    assert hot[0][0] == pytest.approx(2.0)


def test_arrival_model_persistence_rebases_clock(tmp_path):
    path = str(tmp_path / "arrivals.json")
    model = ArrivalModel(path=path, half_life_s=10.0)
    model.observe(GroupKey(n=64), now=50.0)
    model.observe(GroupKey(n=64), now=50.0)
    assert model.save(now=60.0) == path   # decayed to 1.0 at save
    doc = json.load(open(path))
    assert doc["arrivals"][0]["weight"] == pytest.approx(1.0)
    assert "t" not in doc["arrivals"][0]   # no process-local clocks

    # a restart loads the decayed mass at ITS "now" — downtime is not
    # charged against the mix
    loaded = ArrivalModel.load(path, half_life_s=10.0, now=7.0)
    assert loaded.hot(now=7.0)[0][0] == pytest.approx(1.0)
    specs = loaded.hot_specs(now=7.0)
    assert [s.n for s in specs] == [64]


def test_arrival_model_corrupt_file_starts_cold(tmp_path):
    path = str(tmp_path / "arrivals.json")
    with open(path, "w") as fh:
        fh.write("{not json")
    assert ArrivalModel.load(path).hot() == []
    with open(path, "w") as fh:
        json.dump({"schema": 999, "arrivals": []}, fh)
    assert ArrivalModel.load(path).hot() == []


def test_model_path_follows_plan_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("PIFFT_PLAN_CACHE", "off")
    assert model_path() is None
    monkeypatch.setenv("PIFFT_PLAN_CACHE", str(tmp_path))
    assert model_path() == str(tmp_path / "fleet-arrivals.json")


# ------------------------------------------------- shifted load mix


def test_population_schedule_shifted_flips_mix():
    population = [(9.0, {"n": 256, "shifted_weight": 1.0}),
                  (1.0, {"n": 512, "shifted_weight": 9.0})]
    rng = np.random.default_rng(0)
    offsets, draws = loadgen.population_schedule(
        "shifted", population, rps=100.0, duration_s=4.0, rng=rng)
    assert len(offsets) == len(draws) == 400
    t_shift = loadgen.SHIFT_AT_FRAC * 4.0
    pre = [d for off, d in zip(offsets, draws) if off < t_shift]
    post = [d for off, d in zip(offsets, draws) if off >= t_shift]
    assert np.mean(pre) < 0.3 and np.mean(post) > 0.7

    # deterministic given the seed: a replay is only a replay if two
    # runs see the same schedule
    offsets2, draws2 = loadgen.population_schedule(
        "shifted", population, rps=100.0, duration_s=4.0,
        rng=np.random.default_rng(0))
    assert offsets2 == offsets and draws2 == draws


def test_population_schedule_validation_and_defaults():
    rng = np.random.default_rng(1)
    # shifted_weight defaults to weight: no shift in effect
    population = [(1.0, {"n": 64}), (1.0, {"n": 128})]
    _off, draws = loadgen.population_schedule(
        "shifted", population, rps=50.0, duration_s=2.0, rng=rng)
    assert set(draws) == {0, 1}
    with pytest.raises(ValueError, match="shift_frac"):
        loadgen.population_schedule("shifted", population, 50.0, 2.0,
                                    rng, shift_frac=1.5)
    with pytest.raises(ValueError, match="sum to zero"):
        loadgen.population_schedule("uniform",
                                    [(0.0, {"n": 64})], 50.0, 2.0, rng)
    with pytest.raises(ValueError, match="shifted_weight"):
        loadgen.population_schedule(
            "shifted", [(1.0, {"n": 64, "shifted_weight": 0.0})],
            50.0, 2.0, rng)
    assert "shifted" in loadgen.ARRIVAL_PROCESSES


# ------------------------------------------------- plan-store locking


def test_store_lock_serializes_concurrent_writers(
        tmp_path, monkeypatch):
    monkeypatch.setenv("PIFFT_PLAN_CACHE", str(tmp_path))
    keys = [plans.make_key(n) for n in (64, 128, 256, 512)]
    errors = []

    def write(key):
        try:
            plan_cache.store(Plan(key=key, variant="rql", params={},
                                  source="tuned", ms=1.0))
        except Exception as exc:   # pragma: no cover - the assertion
            errors.append(exc)

    threads = [threading.Thread(target=write, args=(k,))
               for k in keys for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    path = plan_cache.store_path(keys[0].device_kind)
    with open(path, encoding="utf-8") as fh:
        stored = json.load(fh)["plans"]
    # no lost update: every key's merge-write survived the race
    assert {k.token() for k in keys} <= set(stored)
    assert not os.path.exists(f"{path}.lock")


def test_store_lock_breaks_stale_locks(tmp_path, monkeypatch):
    monkeypatch.setenv("PIFFT_PLAN_CACHE", str(tmp_path))
    key = plans.make_key(64)
    path = plan_cache.store_path(key.device_kind)
    lock = f"{path}.lock"
    with open(lock, "w") as fh:
        fh.write("999999")   # a dead writer's leftover
    stale = time.time() - 2 * plan_cache._LOCK_STALE_S
    os.utime(lock, (stale, stale))
    plan_cache.store(Plan(key=key, variant="rql", params={},
                          source="tuned", ms=1.0))
    with open(path, encoding="utf-8") as fh:
        assert key.token() in json.load(fh)["plans"]
    assert not os.path.exists(lock)


def test_store_clear_removes_lockfiles(tmp_path, monkeypatch):
    monkeypatch.setenv("PIFFT_PLAN_CACHE", str(tmp_path))
    key = plans.make_key(64)
    path = plan_cache.store_path(key.device_kind)
    plan_cache.store(Plan(key=key, variant="rql", params={},
                          source="tuned", ms=1.0))
    with open(f"{path}.lock", "w"):
        pass
    removed = plan_cache.clear(memory=False, disk=True)
    assert path in removed
    assert not os.path.exists(f"{path}.lock")


# ----------------------------------------------- slomon hot-reload


def _objectives_doc(target_ms):
    return {"windows": [5, 60],
            "objectives": [{"name": "fft-p99", "match": "fft",
                            "p99_target_ms": target_ms,
                            "error_budget": 0.01}]}


def test_slomon_hot_reloads_on_mtime_change(tmp_path, obs_run):
    from cs87project_msolano2_tpu.obs.slomon import (
        SloMonitor,
        load_objectives,
    )

    path = tmp_path / "slo.json"
    path.write_text(json.dumps(_objectives_doc(50)))
    objectives, windows = load_objectives(str(path))
    mon = SloMonitor(objectives, windows=windows)
    mon.watch(str(path))
    history = mon._samples["fft-p99"]

    # unchanged mtime: nothing to do
    assert mon.maybe_reload(now=1000.0) is False

    path.write_text(json.dumps(_objectives_doc(25)))
    os.utime(path, (1, 1))   # force a different mtime
    assert mon.maybe_reload(now=2000.0) is True
    assert mon.objectives[0].p99_target_ms == 25
    # the surviving objective keeps its burn history — it is still
    # valid evidence against the NEW target
    assert mon._samples["fft-p99"] is history
    assert metrics.counter_value("pifft_slo_reloads_total") == 1.0
    reloads = [r for r in events.snapshot()
               if r["kind"] == "slo_reload"]
    assert len(reloads) == 1


def test_slomon_reload_failure_warns_once_keeps_last_good(
        tmp_path, monkeypatch, obs_run):
    from cs87project_msolano2_tpu.obs.slomon import (
        SloMonitor,
        load_objectives,
    )

    warned = []
    monkeypatch.setattr("cs87project_msolano2_tpu.plans.core.warn",
                        lambda msg: warned.append(msg))
    path = tmp_path / "slo.json"
    path.write_text(json.dumps(_objectives_doc(50)))
    objectives, windows = load_objectives(str(path))
    mon = SloMonitor(objectives, windows=windows)
    mon.watch(str(path))

    path.write_text("{not valid json at all")
    os.utime(path, (1, 1))
    assert mon.maybe_reload(now=1000.0) is False
    assert mon.objectives[0].p99_target_ms == 50   # last good set
    assert len(warned) == 1 and "keeping the last good set" in \
        warned[0]

    # the same broken file must not warn again every evaluation tick
    os.utime(path, (2, 2))
    assert mon.maybe_reload(now=2000.0) is False
    assert len(warned) == 1

    # a FIXED file reloads and re-arms the warning
    path.write_text(json.dumps(_objectives_doc(30)))
    os.utime(path, (3, 3))
    assert mon.maybe_reload(now=3000.0) is True
    assert mon.objectives[0].p99_target_ms == 30


# ------------------------------------------------------ event schema


def test_fleet_event_kinds_schema(obs_run):
    events.emit("fleet_drift", shape="s", p_value=0.01,
                live_p99_ms=5.0, baseline_p99_ms=1.0)
    events.emit("fleet_canary", shape="s", promote=True, p_value=0.01)
    events.emit("fleet_promote", token="t", variant="v", p_value=0.01,
                epoch=1)
    events.emit("fleet_rollback", token="t", epoch=1,
                **{"from": "v2", "to": "v1", "kind": "quality",
                   "reason": "p99 did not recover"})
    events.emit("fleet_prewarm", shape="s", weight=3.2)
    recs = events.snapshot()
    assert len(recs) == 5
    for rec in recs:
        assert not events.validate_event(rec), rec
    # a field-less fleet event is schema-INVALID, not silently fine
    events.emit("fleet_promote", token="t")
    bad = events.snapshot()[-1]
    assert any("missing" in p for p in events.validate_event(bad))


def test_fleet_cli_model(tmp_path, monkeypatch, capsys):
    from cs87project_msolano2_tpu.cli import main

    monkeypatch.setenv("PIFFT_PLAN_CACHE", str(tmp_path))
    model = ArrivalModel(path=str(tmp_path / "fleet-arrivals.json"))
    model.observe(GroupKey(n=64), now=1.0)
    model.save(now=1.0)
    assert main(["fleet", "model", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["hot"][0]["n"] == 64
    assert main(["fleet", "model"]) == 0
    assert "n=64" in capsys.readouterr().out
