"""Auxiliary subsystems (SURVEY.md §5): probes, tracing, debug checks,
multi-host wrappers."""

import pytest

from cs87project_msolano2_tpu.probes import how_many_tpu_devices, main as probes_main
from cs87project_msolano2_tpu.utils.debug import (
    assert_disjoint_cover,
    disable_checks,
    enable_checks,
)
from cs87project_msolano2_tpu.utils.tracing import trace


def test_probe_device_count(capsys):
    assert how_many_tpu_devices() >= 8  # virtual CPU mesh in tests
    assert probes_main([]) == 0
    assert int(capsys.readouterr().out.strip()) >= 8


def test_probe_verbose(capsys):
    assert probes_main(["-v"]) == 0
    out = capsys.readouterr().out
    assert "addressable" in out and "device 0" in out


def test_probe_cores(capsys):
    assert probes_main(["--cores"]) == 0
    assert int(capsys.readouterr().out.strip()) >= 1


def test_trace_noop_and_active(tmp_path):
    with trace(None):
        pass  # disabled: pure no-op
    with trace(str(tmp_path / "tr")):
        import jax.numpy as jnp

        _ = jnp.ones(8) * 2
    # best-effort: either a trace dir appeared or profiling was unavailable


def test_debug_nan_check_catches():
    import jax
    import jax.numpy as jnp

    enable_checks()
    try:
        with pytest.raises(FloatingPointError):
            jax.block_until_ready(
                jax.jit(lambda a: a / a)(jnp.zeros(4, jnp.float32))
            )
    finally:
        disable_checks()


def test_assert_disjoint_cover():
    assert_disjoint_cover(64, 8, 8)
    with pytest.raises(AssertionError):
        assert_disjoint_cover(64, 8, 7)


def test_needs_loop_slope_cpu_and_forced(monkeypatch):
    from cs87project_msolano2_tpu.utils.timing import needs_loop_slope

    monkeypatch.delenv("PIFFT_FORCE_LOOP_SLOPE", raising=False)
    assert needs_loop_slope() is False  # tests force the cpu platform
    monkeypatch.setenv("PIFFT_FORCE_LOOP_SLOPE", "1")
    assert needs_loop_slope() is True


def test_loop_slope_measures_and_raises():
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.utils.timing import loop_slope_ms

    # measurable op on CPU: a decently sized matmul
    w = jnp.ones((256, 256), jnp.float32)
    ms = loop_slope_ms(lambda c: (c[0] @ w * 1e-3,), (w,), k1=4, k2=64,
                       reps=1, min_delta_ms=0.5, max_k=1 << 14)
    assert ms > 0
    # an op too fast to resolve must raise, not return garbage
    with pytest.raises(RuntimeError, match="noise floor"):
        loop_slope_ms(lambda c: (c[0] * 1.0,), (jnp.ones(8),), k1=4, k2=8,
                      reps=1, min_delta_ms=1e5, max_k=8)


def test_multihost_noop_without_env(monkeypatch):
    from cs87project_msolano2_tpu.parallel.multihost import (
        global_mesh,
        init_distributed,
    )

    monkeypatch.delenv("PIFFT_COORDINATOR", raising=False)
    assert init_distributed() is False  # no launcher env: no-op
    mesh = global_mesh()
    assert mesh.devices.size >= 8


_MULTIHOST_CHILD = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["PIFFT_REPO"])
from cs87project_msolano2_tpu.parallel.multihost import (
    global_mesh, init_distributed,
)

# env-driven config, exactly how a launcher would set it
assert init_distributed() is True
assert jax.process_count() == 2
assert len(jax.devices()) == 4  # 2 local x 2 processes

import numpy as np
from cs87project_msolano2_tpu.parallel.pi_shard import pi_fft_sharded

mesh = global_mesh()
rng = np.random.default_rng(0)
n = 1024
xr = rng.standard_normal(n).astype(np.float32)
xi = rng.standard_normal(n).astype(np.float32)
yr, yi = jax.jit(lambda a, b: pi_fft_sharded(a, b, mesh))(xr, xi)
jax.block_until_ready((yr, yi))
assert yr.shape == (n,)
print(f"OK process {jax.process_index()}", flush=True)
"""


def test_multihost_two_process_smoke(tmp_path):
    """The initialized path of init_distributed: a real 2-process
    jax.distributed job on localhost (CPU platform), running the sharded
    pi-FFT over the 4-device global mesh."""
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for pid in range(2):
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        env.update(
            PIFFT_REPO=repo,
            PIFFT_COORDINATOR=f"127.0.0.1:{port}",
            PIFFT_NUM_PROCESSES="2",
            PIFFT_PROCESS_ID=str(pid),
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _MULTIHOST_CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    try:
        outs = [p.communicate(timeout=180) for p in procs]
    finally:
        # a child that lost its coordinator blocks forever in
        # jax.distributed.initialize — never leak it into the pytest run
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for pid, (p, (out, err)) in enumerate(zip(procs, outs, strict=True)):
        if p.returncode != 0 and \
                "Multiprocess computations aren't implemented" in err:
            pytest.skip("jax.distributed multiprocess jobs unsupported "
                        "on this host's CPU backend")
        assert p.returncode == 0, f"process {pid} failed:\n{out}\n{err}"
        assert f"OK process {pid}" in out


def test_cli_trace_flag(tmp_path, capsys):
    from cs87project_msolano2_tpu.cli import main

    rc = main(["-n", "64", "-p", "2", "-b", "serial", "-o",
               "--trace", str(tmp_path / "t")])
    assert rc == 0
