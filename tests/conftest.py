"""Test configuration: force JAX onto 8 virtual CPU devices.

Multi-device code paths (shard_map over a Mesh, pmap, collectives) are
exercised on a virtual CPU mesh so the whole suite runs anywhere —
SURVEY.md §4's "multi-device test path using CPU
XLA_FLAGS=--xla_force_host_platform_device_count".

Note: this environment's sitecustomize registers the remote-TPU "axon"
platform at interpreter startup and overrides JAX_PLATFORMS, so the env
var alone is not enough — we also set jax.config after import.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"
# plan subsystem: tier-1 runs offline — no test may write the real user
# cache (or require a TPU to tune).  Tests that exercise the disk store
# monkeypatch PIFFT_PLAN_CACHE to a tmp dir (it is re-read per call).
os.environ["PIFFT_PLAN_CACHE"] = "off"
os.environ.pop("PIFFT_PLAN_AUTOTUNE", None)
# check subsystem: same rule for the summary cache — tests that
# exercise the disk store monkeypatch PIFFT_CHECK_CACHE to a tmp file
# (it is re-read per run).
os.environ["PIFFT_CHECK_CACHE"] = "off"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs[:8]


# --- runtime-assisted guards from the check/ subsystem (docs/CHECKS.md) ---


@pytest.fixture
def recompile_guard():
    """Retrace-budget guard: ``guard.jit(fn, budget=N)`` is a drop-in
    jax.jit whose trace count is verified at teardown — a test that
    makes a guarded function retrace past its budget FAILS, which is
    the point (a silent retrace hides a compile inside a timed window).
    """
    from cs87project_msolano2_tpu.check.runtime import RecompileGuard

    guard = RecompileGuard()
    yield guard
    guard.verify()


@pytest.fixture
def no_tracer_leaks():
    """Arms jax.checking_leaks() for the test: a tracer escaping its
    trace raises here, at the leak, instead of three calls later."""
    from cs87project_msolano2_tpu.check.runtime import tracer_leak_guard

    with tracer_leak_guard():
        yield
