"""L4/L5 tests: harness sweep (append-only TSV, resume) and the law-fit
analysis (the reference's statistical integration test, SURVEY.md §4.2)."""

import importlib.util
import os
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_module(relpath, name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, relpath)
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# shared by the sweep fixture and the law-fit retry below — keep in sync
SWEEP_GRID = dict(backend_name="serial", ns=[4096, 16384], ps=[1, 2, 4, 8],
                  seed=0)


@pytest.fixture(scope="module")
def sweep_tsv(tmp_path_factory):
    # n >= 4096 so per-row serial times are tens of microseconds: at
    # n=256 the phase timers sit at the clock's noise floor and the law
    # fit below (r2 > 0.9) becomes flaky on a loaded machine
    out = tmp_path_factory.mktemp("sweep")
    he = load_module("harness/run_experiments.py", "run_experiments")
    path = he.sweep(reps=3, outdir=str(out), resume=True, **SWEEP_GRID)
    he.verify_pass(**SWEEP_GRID)
    return path


def test_sweep_rows_and_contract(sweep_tsv):
    rows = [l.split("\t") for l in open(sweep_tsv).read().strip().splitlines()]
    assert len(rows) == 2 * 4 * 3  # n-grid x p-grid x reps
    assert all(len(r) == 5 for r in rows)


def test_sweep_resume_skips_done(sweep_tsv):
    he = load_module("harness/run_experiments.py", "run_experiments")
    before = open(sweep_tsv).read()
    path = he.sweep("serial", [4096, 16384], [1, 2, 4, 8], reps=3,
                    outdir=os.path.dirname(sweep_tsv), resume=True, seed=0)
    assert path == sweep_tsv
    assert open(sweep_tsv).read() == before  # nothing re-run


def test_capacity_clipping(tmp_path):
    he = load_module("harness/run_experiments.py", "run_experiments")
    from cs87project_msolano2_tpu.backends.cpu import num_cores

    path = he.sweep("pthreads", [256], [1, 2 * num_cores() * 64], reps=1,
                    outdir=str(tmp_path), resume=False, seed=0)
    rows = open(path).read().strip().splitlines()
    assert len(rows) == 1  # the over-capacity p was clipped


def test_parse_grid():
    he = load_module("harness/run_experiments.py", "run_experiments")
    assert he.parse_grid("1..32") == [1, 2, 4, 8, 16, 32]
    assert he.parse_grid("1024,4096") == [1024, 4096]


def test_law_fit_on_synthetic_data(tmp_path):
    """Data generated exactly from the law (+noise) must pass; data from a
    different law (constant time) must fail the funnel fit."""
    an = load_module("analysis/analyze_results.py", "analyze_results")
    rng = np.random.default_rng(0)
    good = tmp_path / "good.tsv"
    with open(good, "w") as fh:
        for n in (1024, 4096, 16384):
            for p in (1, 2, 4, 8, 16):
                for _ in range(5):
                    fl, tl = an.laws(np.array([float(n)]), np.array([float(p)]))
                    noise = 1 + 0.05 * rng.standard_normal()
                    total = (2e-6 * fl[0] + 3e-6 * tl[0]) * noise + 1e-4
                    fh.write(f"{n}\t{p}\t{total:.6f}\t"
                             f"{2e-6 * fl[0] * noise:.6f}\t"
                             f"{3e-6 * tl[0] * noise:.6f}\n")
    rep = an.analyze(str(good))
    assert all(rep[k]["holds"] for k in ("total", "funnel", "tube"))
    assert abs(rep["funnel"]["beta"] - 2e-6) / 2e-6 < 0.05
    assert abs(rep["tube"]["beta"] - 3e-6) / 3e-6 < 0.05


def test_law_fit_on_real_sweep(sweep_tsv):
    """The serial backend's per-processor phase timers must obey the law
    (the project's own 'scales as designed' verification).  The binding
    criterion is the significance test (alpha), exactly as in the
    reference's R scripts; R^2 is only sanity-bounded loosely because
    this is a REAL timing sweep and a loaded CI machine adds noise the
    law fit legitimately absorbs (measured 0.83 under full-suite load,
    >0.95 on a quiet machine; 0.75 keeps margin below that floor while
    still catching fit-quality regressions alpha alone would miss).  A
    transient load spike (e.g. a concurrent sweep client on this one
    core) can push a single sweep below the bound, so on failure the
    sweep is re-measured once before declaring a regression."""
    an = load_module("analysis/analyze_results.py", "analyze_results")
    he = load_module("harness/run_experiments.py", "run_experiments")
    rep = an.analyze(sweep_tsv)
    if min(rep["funnel"]["r2"], rep["tube"]["r2"]) <= 0.75:
        import tempfile
        with tempfile.TemporaryDirectory() as retry_dir:
            path = he.sweep(reps=3, outdir=retry_dir, resume=True,
                            **SWEEP_GRID)
            rep = an.analyze(path)
    # a 3-rep CI smoke sweep on a loaded 1-core host verifies the
    # harness->analysis integration and the scaling DIRECTION
    # (significance), not the round-5 per-cell prediction gate — that
    # demands replication depth only the committed datasets carry
    # (tests/test_committed_datasets.py gates those at full strength)
    assert rep["funnel"]["signif"] and rep["tube"]["signif"]
    assert rep["funnel"]["r2"] > 0.75
    assert rep["tube"]["r2"] > 0.75


def test_law_fit_on_chip_model(tmp_path):
    """Synthetic data generated from the on-chip law (funnel n(p-1),
    tube n*log2(n/p) — all p virtual processors on one accelerator) must
    pass under the on-chip model, which auto-selects for TPU-backend
    filenames."""
    an = load_module("analysis/analyze_results.py", "analyze_results")
    rng = np.random.default_rng(1)
    path = tmp_path / "fourier-parallel-pi-pallas-results.tsv"
    with open(path, "w") as fh:
        for n in (2**18, 2**19, 2**20):
            for p in (1, 4, 16, 64):
                for _ in range(5):
                    fl, tl = an.laws(np.array([float(n)]),
                                     np.array([float(p)]), "on-chip")
                    noise = 1 + 0.05 * rng.standard_normal()
                    fm = 4e-7 * fl[0] * noise
                    tm = 6e-9 * tl[0] * noise
                    fh.write(f"{n}\t{p}\t{fm + tm:.6f}\t{fm:.6f}\t{tm:.6f}\n")
    assert an.model_for(str(path)) == "on-chip"
    rep = an.analyze(str(path))
    assert rep["model"] == "on-chip"
    assert all(rep[k]["holds"] for k in ("total", "funnel", "tube"))
    # the same data must NOT fit the per-processor funnel law
    rep_pp = an.analyze(str(path), model="per-processor")
    assert rep_pp["funnel"]["r2"] < rep["funnel"]["r2"]


def test_serialized_model_is_hybrid(tmp_path):
    """The serialized regime times total_ms as the SUM over processors
    (total-work laws) but the funnel/tube columns as processor 0's own
    timers (per-processor laws) — native/pifft_backends.c:62-67.  Data
    generated exactly that way must pass all three fits under the
    serialized model (round-3 advisor: the non-hybrid fit dropped the
    tube R^2 to ~0.69 on a real serial sweep)."""
    an = load_module("analysis/analyze_results.py", "analyze_results")
    path = tmp_path / "fourier-parallel-pi-serial-results.tsv"
    _write_synthetic_tsv(an, path, seed=7, hybrid_serialized=True)
    assert an.model_for(str(path)) == "serialized"
    rep = an.analyze(str(path))
    assert all(rep[k]["holds"] for k in ("total", "funnel", "tube"))
    assert rep["funnel"]["r2"] > 0.9 and rep["tube"]["r2"] > 0.9
    assert rep["total"]["r2"] > 0.9


def test_oversub_filename_and_model(tmp_path, monkeypatch):
    """--oversubscribe sweeps land in a distinct -oversub- TSV that the
    analysis (python and awk) auto-maps to the serialized model, keeping
    resume and model selection regime-consistent (round-3 advisor)."""
    he = load_module("harness/run_experiments.py", "run_experiments")
    an = load_module("analysis/analyze_results.py", "analyze_results")
    # pin capacity to 1 so the sweep is oversubscribed regardless of the
    # host's real core count
    real_get = he.get_backend

    def capped(name):
        b = real_get(name)
        b.capacity = lambda: 1
        return b

    monkeypatch.setattr(he, "get_backend", capped)
    path = he.sweep("pthreads", [1024], [1, 2, 4], reps=1,
                    outdir=str(tmp_path), resume=True, seed=0,
                    oversubscribe=True)
    assert "-pthreads-oversub-results.tsv" in path
    rows = open(path).read().strip().splitlines()
    assert len(rows) == 3  # p-grid NOT clipped to the 1-core capacity
    assert an.model_for(path) == "serialized"
    # normal (non-oversub) sweeps keep the plain filename
    assert "-oversub-" not in he.result_path(str(tmp_path), "pthreads")


def _write_synthetic_tsv(an, path, model="per-processor", seed=11,
                         hybrid_serialized=False):
    """Deterministic law-obeying TSV for plumbing tests: dispatcher and
    fallback tests must not depend on live timing on a loaded 1-core
    host (observed: real-sweep-based dispatcher tests flake when a
    concurrent TPU sweep competes for the core).

    hybrid_serialized=True emits serialized-REGIME rows: funnel/tube
    columns are processor-0's per-processor timers, total is the sum
    over all p processors — the shape the hybrid serialized model fits
    (native/pifft_backends.c:62-67)."""
    rng = np.random.default_rng(seed)
    if hybrid_serialized:
        model = "per-processor"
    with open(path, "w") as fh:
        for n in (1024, 4096, 16384):
            for p in (1, 2, 4, 8, 16):
                for _ in range(5):
                    fl, tl = an.laws(np.array([float(n)]),
                                     np.array([float(p)]), model)
                    noise = 1 + 0.03 * rng.standard_normal()
                    fm = 2e-6 * fl[0] * noise
                    tm = 3e-6 * tl[0] * noise
                    total = p * (fm + tm) if hybrid_serialized else fm + tm
                    fh.write(f"{n}\t{p}\t{total:.6f}\t{fm:.6f}\t{tm:.6f}\n")


def test_dispatcher_forwards_model(tmp_path):
    """The bash dispatcher must accept and forward --model (round-3
    advisor: the harness's hint was un-followable through this entry)."""
    an = load_module("analysis/analyze_results.py", "analyze_results")
    # serialized-regime data: per-processor phase columns, summed total
    path = tmp_path / "results.tsv"
    _write_synthetic_tsv(an, path, seed=13, hybrid_serialized=True)
    r = subprocess.run(
        [os.path.join(REPO, "analysis", "analyze-results"),
         "--model", "serialized", str(path)],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr
    assert "law model: serialized" in r.stdout


def test_degraded_rows_excluded(tmp_path):
    """Rows marked DEGRADED (dispatch-inclusive fallback timing) must not
    enter the fit."""
    an = load_module("analysis/analyze_results.py", "analyze_results")
    rng = np.random.default_rng(2)
    # per-processor-law data needs a per-processor filename: the round-5
    # falsifiable criterion RIGHTLY rejects this data under the
    # serialized model the old -serial- name would auto-select
    path = tmp_path / "fourier-parallel-pi-pthreads-results.tsv"
    with open(path, "w") as fh:
        for n in (1024, 4096, 16384):
            for p in (1, 2, 4, 8, 16):
                for _ in range(3):
                    fl, tl = an.laws(np.array([float(n)]),
                                     np.array([float(p)]))
                    noise = 1 + 0.05 * rng.standard_normal()
                    fm = 2e-6 * fl[0] * noise
                    tm = 3e-6 * tl[0] * noise
                    fh.write(f"{n}\t{p}\t{fm + tm:.6f}\t{fm:.6f}\t{tm:.6f}\n")
        # poisoned rows: ~100 ms of relay overhead, properly marked
        for p in (1, 2, 4, 8, 16):
            fh.write(f"64\t{p}\t100.0\t50.0\t50.0\tDEGRADED\n")
    data, degraded = an.load_tsv(str(path))
    assert degraded == 5
    assert not (data[:, 0] == 64).any()
    rep = an.analyze(str(path))
    assert all(rep[k]["holds"] for k in ("total", "funnel", "tube"))


def test_harness_marks_degraded_rows(tmp_path, monkeypatch):
    """A backend reporting degraded timers must produce a 6th-column
    marker, and resume must still count the row as done."""
    he = load_module("harness/run_experiments.py", "run_experiments")
    from cs87project_msolano2_tpu.backends import registry
    from cs87project_msolano2_tpu.backends.base import RunResult

    class FakeBackend:
        name = "serial"

        def capacity(self):
            return None

        def run(self, x, p, reps=1, fetch=True, timers=True):
            return RunResult(out=None, total_ms=100.0, funnel_ms=50.0,
                             tube_ms=50.0, degraded=True)

    monkeypatch.setattr(registry, "get_backend", lambda name: FakeBackend())
    monkeypatch.setattr(he, "get_backend", lambda name: FakeBackend())
    path = he.sweep("serial", [256], [1, 2], reps=1, outdir=str(tmp_path),
                    resume=True, seed=0)
    rows = [l.split("\t") for l in open(path).read().strip().splitlines()]
    assert all(len(r) == 6 and r[5] == "DEGRADED" for r in rows)
    assert he.done_counts(path)[(256, 1)] == 1


def test_dispatcher_and_awk_fallback(tmp_path):
    """The bash dispatcher runs the full analysis; the awk fallback must
    agree with the python fit to ~3 significant digits."""
    an = load_module("analysis/analyze_results.py", "analyze_results")
    tsv = str(tmp_path / "results.tsv")
    _write_synthetic_tsv(an, tsv)
    full = subprocess.run(
        [os.path.join(REPO, "analysis", "analyze-results"), tsv],
        capture_output=True, text=True,
    )
    assert full.returncode == 0, full.stderr
    assert "law holds: Yes" in full.stdout

    awk = subprocess.run(
        ["awk", "-f", os.path.join(REPO, "analysis", "analyze-results.awk"),
         tsv],
        capture_output=True, text=True,
    )
    assert awk.returncode == 0
    rep = an.analyze(tsv)
    # the round-5 awk prints the two-coefficient fit as
    # "fit: total_ms ~ funnel=… + tube=… [+ floor=…]" — both law
    # coefficients must agree with the python fit
    import re
    coefs = dict(re.findall(r"(funnel|tube|floor)=([-0-9.e+]+)", awk.stdout))
    assert abs(float(coefs["funnel"]) - rep["total"]["beta_f"]) \
        / abs(rep["total"]["beta_f"]) < 1e-3
    assert abs(float(coefs["tube"]) - rep["total"]["beta_t"]) \
        / abs(rep["total"]["beta_t"]) < 1e-3


def test_awk_fallback_on_chip_model_and_degraded(tmp_path):
    """The awk fallback must mirror the python analysis: on-chip law for
    TPU-backend filenames, DEGRADED rows excluded."""
    an = load_module("analysis/analyze_results.py", "analyze_results")
    rng = np.random.default_rng(3)
    path = tmp_path / "fourier-parallel-pi-jax-results.tsv"
    with open(path, "w") as fh:
        for n in (2**16, 2**18, 2**20):
            for p in (1, 4, 16):
                for _ in range(4):
                    fl, tl = an.laws(np.array([float(n)]),
                                     np.array([float(p)]), "on-chip")
                    noise = 1 + 0.03 * rng.standard_normal()
                    fm = 4e-7 * fl[0] * noise
                    tm = 6e-9 * tl[0] * noise
                    fh.write(f"{n}\t{p}\t{fm + tm:.6f}\t{fm:.6f}\t{tm:.6f}\n")
        fh.write("64\t2\t100.0\t50.0\t50.0\tDEGRADED\n")
    awk = subprocess.run(
        ["awk", "-f", os.path.join(REPO, "analysis", "analyze-results.awk"),
         str(path)],
        capture_output=True, text=True,
    )
    assert awk.returncode == 0, awk.stderr
    assert "law model: on-chip" in awk.stdout
    assert "excluded 1 DEGRADED" in awk.stdout
    assert "law holds: Yes" in awk.stdout
    # and the fitted coefficients agree with the python fit
    rep = an.analyze(str(path))
    import re
    coefs = dict(re.findall(r"(funnel|tube|floor)=([-0-9.e+]+)", awk.stdout))
    assert abs(float(coefs["funnel"]) - rep["total"]["beta_f"]) \
        / abs(rep["total"]["beta_f"]) < 1e-3
    assert abs(float(coefs["tube"]) - rep["total"]["beta_t"]) \
        / abs(rep["total"]["beta_t"]) < 1e-3


def test_missing_results_guard():
    r = subprocess.run(
        [os.path.join(REPO, "analysis", "analyze-results"),
         "/nonexistent/results.tsv"],
        capture_output=True, text=True,
    )
    assert r.returncode == 1
    assert "run the experiments first" in r.stderr
