"""Tests for the analyze/ subsystem (docs/ANALYSIS.md): the loader
over all three measurement sources, the law-fit core (coefficient
recovery with confidence intervals, the prediction gate's teeth),
span-derived phase attribution vs the TSV derivation, the statistical
perf-regression gate (Mann-Whitney over replications, the calibrated
scalar fallback, fingerprint-gated comparability, the committed
perf-baseline), and the `pifft analyze {fit,report,gate}` CLI.

The capstone pair is the ISSUE 9 acceptance criterion:
``test_gate_committed_trajectory_passes`` (the committed BENCH_r01-r06
rounds must gate clean) and ``test_gate_flags_injected_slowdown``
(a synthetic round with a 30% slowdown must fail the gate with a named
metric and a p-value).
"""

import json
import os

import numpy as np
import pytest

from cs87project_msolano2_tpu.analyze import lawfit, phases, regress
from cs87project_msolano2_tpu.analyze.loader import (
    Fingerprint,
    build_table,
    load_bench_round,
    load_bench_rounds,
    load_obs_samples,
    load_tsv_samples,
)
from cs87project_msolano2_tpu.analyze.records import (
    dump_record,
    env_fingerprint,
    validate_record,
)
from cs87project_msolano2_tpu.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMMITTED_ROUNDS = [os.path.join(REPO, f"BENCH_r0{i}.json")
                    for i in range(1, 7)]


# ---------------------------------------------------------- fixtures


def write_tsv(path, rows):
    with open(path, "w") as fh:
        for row in rows:
            fh.write("\t".join(str(v) for v in row) + "\n")
    return str(path)


def make_phase_rows(seed=0, ns=(1024, 4096), ps=(1, 2, 4, 8), reps=3):
    """Deterministic per-processor-law phase rows (n p total funnel
    tube) shared by the TSV-vs-span agreement tests."""
    rng = np.random.default_rng(seed)
    rows = []
    for n in ns:
        for p in ps:
            fl, tl = lawfit.laws(np.array([float(n)]),
                                 np.array([float(p)]))
            for _ in range(reps):
                eps = 1 + 0.05 * rng.standard_normal()
                fm = 2e-6 * fl[0] * eps
                tm = 3e-6 * tl[0] * eps
                rows.append([n, p, round(fm + tm, 9), round(fm, 9),
                             round(tm, 9)])
    return rows


def write_span_events(path, rows, run="testrun", with_env=True,
                      truncate_tail=False):
    """The same phase rows as an obs event stream: one funnel + one
    tube span event per row, the shape obs.events/record_span writes."""
    seq = 0
    lines = []

    def event(kind, cell=None, payload=None):
        nonlocal seq
        rec = {"v": 1, "run": run, "seq": seq, "t": 0.001 * seq,
               "kind": kind}
        if cell:
            rec["cell"] = cell
        if payload:
            rec["payload"] = payload
        seq += 1
        return json.dumps(rec)

    if with_env:
        lines.append(event("env", payload={
            "platform": "cpu", "device_kind": "cpu-test", "smoke": True}))
    for n, p, _total, fm, tm in rows:
        cell = {"n": int(n), "p": int(p)}
        for name, ms in (("funnel", fm), ("tube", tm)):
            lines.append(event("span", cell=cell, payload={
                "name": name, "ts_s": 0.0, "dur_s": ms / 1e3,
                "tid": 1, "depth": 1, "parent": "cell"}))
    text = "\n".join(lines) + "\n"
    if truncate_tail:
        text += '{"v": 1, "run": "testrun", "seq": 9999, "ki'
    with open(path, "w") as fh:
        fh.write(text)
    return str(path)


def write_round(path, index, metrics, env=None, smoke=None, bare=True,
                tail=""):
    """A BENCH round file: bare record or driver wrapper."""
    parsed = {"metric": "fft1d_n2^20_complex64_gflops",
              "unit": "GFLOP/s"}
    parsed["value"] = metrics.pop("__value__", 1000.0)
    parsed.update(metrics)
    if env is not None:
        parsed["env"] = env
    if smoke:
        parsed["smoke"] = True
    doc = parsed if bare else {"n": index, "cmd": "python bench.py",
                               "rc": 0, "tail": tail, "parsed": parsed}
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return str(path)


# ------------------------------------------------------------- loader


def test_loader_tsv_samples_and_degraded_flag(tmp_path):
    rows = make_phase_rows()
    path = write_tsv(tmp_path / "sweep.tsv", rows)
    with open(path, "a") as fh:
        fh.write("64\t2\t100.0\t50.0\t50.0\tDEGRADED\n")
    samples = load_tsv_samples(path)
    # 3 phase samples per row, plus the degraded row's 3 flagged ones
    assert len(samples) == 3 * len(rows) + 3
    degraded = [s for s in samples if s.degraded]
    assert len(degraded) == 3 and degraded[0].n == 64
    # rep indices count occurrences per (n, p) cell
    reps = {s.rep for s in samples if s.n == 1024 and s.p == 1
            and s.metric == "total_ms"}
    assert reps == {0, 1, 2}


def test_loader_refuses_unknown_row_marker(tmp_path):
    """The loader enforces the same provenance refusal as the fit's
    reader: an unknown 6th-column marker must raise, not silently
    ingest as clean data."""
    path = write_tsv(tmp_path / "sweep.tsv", make_phase_rows())
    with open(path, "a") as fh:
        fh.write("64\t2\t100.0\t50.0\t50.0\tPARTIAL\n")
    with pytest.raises(ValueError, match="unknown row marker"):
        load_tsv_samples(path)


def test_loader_obs_stream_with_truncated_tail(tmp_path):
    rows = make_phase_rows()
    path = write_span_events(tmp_path / "ev.jsonl", rows,
                             truncate_tail=True)
    samples, fp, dropped = load_obs_samples(path)
    assert dropped == 1  # the half-written tail is skipped, not fatal
    assert fp is not None and fp.platform == "cpu" and fp.smoke
    # funnel+tube+total per row
    assert len(samples) == 3 * len(rows)


def test_loader_bench_round_fingerprint_stamped_and_backfilled(tmp_path):
    # stamped round: env wins
    p1 = write_round(tmp_path / "bench_r07.json", 7,
                     {"__value__": 1200.0},
                     env={"platform": "axon", "device_kind": "v5e",
                          "smoke": False, "git_rev": "abc123"})
    r7 = load_bench_round(p1)
    assert r7.index == 7  # from the _rNN filename for bare records
    assert r7.fingerprint == Fingerprint("axon", "v5e", False, "abc123")
    assert r7.metrics["fft1d_n2^20_complex64_gflops"] == 1200.0
    # unstamped wrapper round: smoke flag + platform banner backfill
    p2 = write_round(tmp_path / "old.json", 3, {"__value__": 900.0},
                     bare=False,
                     tail="WARNING: Platform 'axon' is experimental\n")
    r3 = load_bench_round(p2)
    assert r3.index == 3  # the wrapper's "n"
    assert r3.fingerprint.platform == "axon"
    assert r3.fingerprint.smoke is False
    assert r3.fingerprint.device_kind is None  # unrecoverable stays None


def test_loader_committed_rounds_backfill():
    rounds = load_bench_rounds(COMMITTED_ROUNDS)
    assert [r.index for r in rounds] == [1, 2, 3, 4, 5, 6]
    for r in rounds[:5]:
        assert r.fingerprint.platform == "axon", r.path
        assert not r.fingerprint.smoke
    assert rounds[5].fingerprint.smoke  # r06 is the offline smoke round
    ok, reason = rounds[4].fingerprint.compatible(rounds[5].fingerprint)
    assert not ok and "smoke" in reason
    # replicated-vs-scalar: committed rounds are scalar metrics
    assert all(isinstance(v, float)
               for r in rounds for v in r.metrics.values())


def test_loader_replicated_metric_kept_whole(tmp_path):
    path = write_round(tmp_path / "bench_r09.json", 9,
                       {"tput_gflops": [990.0, 1000.0, 1010.0]},
                       env=env_fingerprint())
    rnd = load_bench_round(path)
    assert rnd.metrics["tput_gflops"] == [990.0, 1000.0, 1010.0]


def test_loader_parses_serve_mesh_rows(tmp_path):
    """The serve_mesh row set (bench.py --serve-mesh —
    docs/SERVING.md): per-device utilization becomes ONE replicated
    metric with device-tagged samples, and the kill row's p99 split
    becomes the scalar metrics a future gate can hold floors on."""
    from cs87project_msolano2_tpu.analyze.loader import bench_samples

    rows = [
        {"row": "device", "device": "vdev0", "state": "dead",
         "served": 10, "busy_s": 0.72, "utilization": 0.16},
        {"row": "device", "device": "vdev1", "state": "healthy",
         "served": 28, "busy_s": 0.32, "utilization": 0.07},
        {"row": "kill", "killed_device": "vdev0", "t_kill_s": 0.6,
         "p99_pre_kill_ms": 15.8, "p99_post_kill_ms": 50.6,
         "requests": 144, "completed": 144, "rejected": 0,
         "failed": 0, "failover_tagged": 1},
    ]
    path = write_round(tmp_path / "bench_r12.json", 12,
                       {"serve_mesh": rows}, env=env_fingerprint(),
                       smoke=True)
    rnd = load_bench_round(path)
    assert rnd.metrics["serve_mesh_utilization"] == [0.16, 0.07]
    assert rnd.metrics["serve_mesh_p99_pre_kill_ms"] == 15.8
    assert rnd.metrics["serve_mesh_p99_post_kill_ms"] == 50.6
    assert len(rnd.serve_mesh_rows) == 3
    samples = bench_samples(rnd)
    util = [s for s in samples if s.metric == "serve_mesh_utilization"]
    assert [(s.device, s.value) for s in util] \
        == [("vdev0", 0.16), ("vdev1", 0.07)]
    post = [s for s in samples
            if s.metric == "serve_mesh_p99_post_kill_ms"]
    assert len(post) == 1 and post[0].value == 50.6 \
        and post[0].device is None


def test_loader_pre_mesh_rounds_have_no_mesh_rows(tmp_path):
    path = write_round(tmp_path / "bench_r02.json", 2,
                       {"tput_gflops": 900.0}, env=env_fingerprint())
    rnd = load_bench_round(path)
    assert rnd.serve_mesh_rows == []
    assert "serve_mesh_utilization" not in rnd.metrics


def test_build_table_merges_all_three_sources(tmp_path):
    rows = make_phase_rows()
    tsv = write_tsv(tmp_path / "sweep.tsv", rows)
    ev = write_span_events(tmp_path / "ev.jsonl", rows)
    rnd = write_round(tmp_path / "bench_r01.json", 1,
                      {"__value__": 737.1, "vs_baseline": 211.4})
    table = build_table([tsv], [rnd], [ev])
    summary = table.summary()
    assert summary["by_source"] == {"tsv": 3 * len(rows),
                                    "obs": 3 * len(rows), "bench": 2}
    assert len(table.rounds) == 1
    assert table.phase_rows("tsv").shape == (len(rows), 5)
    assert table.phase_rows("obs").shape == (len(rows), 5)


# ------------------------------------------------------------- lawfit


def test_fit_recovers_coefficients_with_ci_coverage():
    """Homoscedastic law data: the fit must recover the true betas and
    its 95% CIs must cover them (per-seed determinism; the CI is the
    package-era extension a cross-round comparison anchors on)."""
    rng = np.random.default_rng(42)
    rows = []
    for n in (1024, 4096, 16384):
        for p in (1, 2, 4, 8, 16):
            fl, tl = lawfit.laws(np.array([float(n)]),
                                 np.array([float(p)]))
            for _ in range(6):
                # homoscedastic noise well under the smallest cell's
                # phase time, so OLS standard errors (and hence the
                # CIs) are exact for this design
                fm = 2e-6 * fl[0] + 2e-5 * rng.standard_normal()
                tm = 3e-6 * tl[0] + 2e-5 * rng.standard_normal()
                rows.append([n, p, fm + tm, fm, tm])
    rep = lawfit.analyze_table(np.asarray(rows), "per-processor",
                               verbose=False)
    assert all(rep[k]["holds"] for k in ("total", "funnel", "tube"))
    for phase, true_beta in (("funnel", 2e-6), ("tube", 3e-6)):
        beta = rep[phase]["beta"]
        assert abs(beta - true_beta) / true_beta < 0.05
        lo, hi = rep[phase]["ci95"][phase]
        assert lo <= true_beta <= hi, (phase, lo, true_beta, hi)
        assert lo < beta < hi
    # per-cell residuals ride the total fit
    cells = rep["cells"]
    assert len(cells) == 15
    assert all(abs(c["log_ratio"]) < 0.2 for c in cells)


def test_prediction_gate_rejects_law_violating_data():
    """Constant-time data correlates with nothing: the fit must fail
    (significance or the per-cell prediction gate — the round-5
    falsifiability requirement)."""
    rng = np.random.default_rng(7)
    rows = []
    for n in (1024, 4096, 16384):
        for p in (1, 2, 4, 8, 16):
            for _ in range(5):
                t = 5.0 * (1 + 0.05 * rng.standard_normal())
                rows.append([n, p, t, t / 2, t / 2])
    rep = lawfit.analyze_table(np.asarray(rows), "per-processor",
                               verbose=False)
    assert rep["total"]["holds"] is False
    assert rep["funnel"]["holds"] is False


def test_demo_table_roundtrip(tmp_path):
    path = lawfit.write_demo_tsv(str(tmp_path / "demo.tsv"))
    rep = lawfit.analyze(path, verbose=False)
    assert rep["total"]["holds"] is True
    assert abs(rep["funnel"]["beta"] - 2e-6) / 2e-6 < 0.05


def test_t_ppf_fallback_matches_scipy():
    scipy = pytest.importorskip("scipy")
    from unittest import mock

    for q, df in ((0.025, 30), (0.05, 8)):
        want = float(scipy.stats.t.isf(q, df))
        with mock.patch.dict("sys.modules", {"scipy": None,
                                             "scipy.stats": None}):
            got = lawfit.t_ppf(q, df)
        # the fallback is the normal approximation: exact agreement is
        # not expected at small df, but the CI must not be wild
        assert abs(got - want) / want < 0.12, (q, df, got, want)


# ----------------------------------------------- phase attribution


def test_span_shares_match_tsv_shares_on_same_run(tmp_path):
    """The acceptance criterion: funnel/tube shares derived from obs
    spans must agree with TSV-derived shares on the same synthetic
    run."""
    from cs87project_msolano2_tpu.obs.events import load_events

    rows = make_phase_rows()
    tsv = write_tsv(tmp_path / "sweep.tsv", rows)
    ev = write_span_events(tmp_path / "ev.jsonl", rows)
    records, dropped = load_events(ev)
    assert dropped == 0
    from_spans = phases.phase_shares_from_events(records)
    from_tsv = phases.phase_shares(None, tsv_path=tsv)
    assert set(from_spans) == set(from_tsv)
    for cell in from_tsv:
        for k in ("funnel", "tube"):
            assert from_spans[cell][k] == pytest.approx(
                from_tsv[cell][k], abs=1e-6), (cell, k)
        assert from_spans[cell]["runs"] == from_tsv[cell]["runs"]
    # and the span-derived table must pass the same law fit
    span_rows = phases.phase_rows_from_events(records)
    rep = lawfit.analyze_table(span_rows, "per-processor", verbose=False)
    assert all(rep[k]["holds"] for k in ("total", "funnel", "tube"))


def test_span_pairing_drops_incomplete_runs(tmp_path):
    rows = make_phase_rows(ns=(1024,), ps=(2,), reps=2)
    ev = write_span_events(tmp_path / "ev.jsonl", rows)
    # append a funnel span with no matching tube (killed mid-run)
    with open(ev, "a") as fh:
        fh.write(json.dumps({
            "v": 1, "run": "testrun", "seq": 500, "t": 5.0,
            "kind": "span", "cell": {"n": 1024, "p": 2},
            "payload": {"name": "funnel", "ts_s": 5.0, "dur_s": 0.001,
                        "tid": 1, "depth": 1}}) + "\n")
    from cs87project_msolano2_tpu.obs.events import load_events

    records, _ = load_events(ev)
    assert len(phases.phase_rows_from_events(records)) == len(rows)


# ---------------------------------------------------------- regress


def test_direction_classification():
    assert regress.direction_of("fft1d_n2^20_complex64_gflops") == \
        "higher"
    assert regress.direction_of("n2^22_ms") == "lower"
    assert regress.direction_of("vs_baseline") == "higher"
    assert regress.direction_of("serve_slo_p99_ms") == "lower"
    assert regress.direction_of("n2^13_carry_passes") is None


def test_mann_whitney_separated_and_identical():
    a = [10.0, 11.0, 12.0, 10.5, 11.5]
    b = [7.0, 7.5, 8.0, 7.2, 7.8]
    _, p = regress.mann_whitney(a, b)   # H1: b smaller — true here
    assert p < 0.01
    _, p_same = regress.mann_whitney(a, a)
    assert p_same > 0.3


def _quiet_rounds(tmp_path, count=4, seed=3, reps=8):
    """A quiet replicated trajectory: same distribution each round."""
    rng = np.random.default_rng(seed)
    paths = []
    for i in range(1, count + 1):
        vals = [round(float(v), 3)
                for v in 1000.0 + 15.0 * rng.standard_normal(reps)]
        paths.append(write_round(
            tmp_path / f"bench_r{i:02d}.json", i,
            {"__value__": float(np.mean(vals)), "tput_gflops": vals},
            env={"platform": "cpu", "device_kind": "test",
                 "smoke": False}))
    return paths


def test_gate_quiet_on_resampled_noise(tmp_path):
    rounds = load_bench_rounds(_quiet_rounds(tmp_path))
    result = regress.gate_rounds(rounds)
    assert result.ok, [r.describe() for r in result.new]


def test_gate_flags_injected_slowdown_replicated(tmp_path):
    """A 30% slowdown over replications must flag via Mann-Whitney
    with a real p-value; resampled noise (the rounds before it) must
    not."""
    paths = _quiet_rounds(tmp_path)
    rng = np.random.default_rng(9)
    bad = [round(float(v), 3)
           for v in 700.0 + 15.0 * rng.standard_normal(8)]
    paths.append(write_round(
        tmp_path / "bench_r05.json", 5,
        {"__value__": float(np.mean(bad)), "tput_gflops": bad},
        env={"platform": "cpu", "device_kind": "test", "smoke": False}))
    result = regress.gate_rounds(load_bench_rounds(paths))
    assert not result.ok
    flagged = {r.metric: r for r in result.new}
    assert "tput_gflops" in flagged
    reg = flagged["tput_gflops"]
    assert reg.test == "mann-whitney"
    assert reg.p_value < 0.01
    assert reg.change < -0.25
    # only the injected pair flags, not the quiet history
    assert all(r.to_round == 5 for r in result.new)


def test_gate_scalar_slowdown_and_leave_one_out(tmp_path):
    """Scalar rounds: a quiet history then a 30% drop — the calibrated
    z must flag it, and the injected step must not widen its own
    tolerance (leave-one-pair-out)."""
    paths = []
    values = [1000.0, 1015.0, 995.0, 1005.0, 1010.0]
    for i, v in enumerate(values, start=1):
        paths.append(write_round(
            tmp_path / f"bench_r{i:02d}.json", i,
            {"__value__": v, "large_n_gflops": v * 0.9},
            env={"platform": "cpu", "device_kind": "test",
                 "smoke": False}))
    ok = regress.gate_rounds(load_bench_rounds(paths))
    assert ok.ok
    paths.append(write_round(
        tmp_path / "bench_r06.json", 6,
        {"__value__": 700.0, "large_n_gflops": 630.0},
        env={"platform": "cpu", "device_kind": "test", "smoke": False}))
    result = regress.gate_rounds(load_bench_rounds(paths))
    assert not result.ok
    assert {r.metric for r in result.new} == \
        {"fft1d_n2^20_complex64_gflops", "large_n_gflops"}
    assert all(r.test == "scalar-z" and r.p_value < 0.05
               for r in result.new)


def test_gate_refuses_cross_environment_comparison(tmp_path):
    """A smoke round after a hardware round is SKIPPED (reported), not
    compared — even with a catastrophic apparent drop."""
    p1 = write_round(tmp_path / "bench_r01.json", 1,
                     {"__value__": 1300.0},
                     env={"platform": "axon", "device_kind": "v5e",
                          "smoke": False})
    p2 = write_round(tmp_path / "bench_r02.json", 2, {"__value__": 1.4},
                     env={"platform": "cpu", "device_kind": "cpu",
                          "smoke": True}, smoke=True)
    result = regress.gate_rounds(load_bench_rounds([p1, p2]))
    assert result.ok
    assert len(result.skipped_pairs) == 1
    assert "smoke" in result.skipped_pairs[0]["reason"]
    assert result.candidates == []


def test_gate_baseline_accepts_and_reports_fixed(tmp_path):
    paths = _quiet_rounds(tmp_path, count=3)
    rng = np.random.default_rng(11)
    bad = [round(float(v), 3)
           for v in 700.0 + 15.0 * rng.standard_normal(8)]
    paths.append(write_round(
        tmp_path / "bench_r04.json", 4,
        {"__value__": float(np.mean(bad)), "tput_gflops": bad},
        env={"platform": "cpu", "device_kind": "test", "smoke": False}))
    rounds = load_bench_rounds(paths)
    failing = regress.gate_rounds(rounds)
    assert not failing.ok
    # write the regressions into a baseline: the gate must now pass
    bl_path = str(tmp_path / "perf-baseline.json")
    regress.write_perf_baseline(bl_path, failing.new)
    baseline = regress.load_perf_baseline(bl_path)
    accepted = regress.gate_rounds(rounds, baseline)
    assert accepted.ok
    assert {r.metric for r in accepted.accepted} == \
        {r.metric for r in failing.new}
    # a stale baseline entry is reported fixed, not an error
    stale = baseline + [("ghost_metric", 1, 2)]
    res = regress.gate_rounds(rounds, stale)
    assert res.ok and ("ghost_metric", 1, 2) in res.fixed


def test_change_points_name_largest_step(tmp_path):
    rounds = load_bench_rounds(COMMITTED_ROUNDS)
    cps = regress.change_points(rounds)
    # the headline's biggest step is the r02->r03 fused-kernel landing
    cp = cps["fft1d_n2^20_complex64_gflops"]
    assert (cp["from_round"], cp["to_round"]) == (2, 3)
    assert cp["change"] > 0.3


# ------------------------------------------- the acceptance criterion


def test_gate_committed_trajectory_passes():
    """ISSUE 9 acceptance: `pifft analyze gate` over the committed
    BENCH_r01-r06 trajectory exits 0 (with the committed baseline),
    and the r05->r06 smoke/hardware pair is refused, not compared."""
    rc = cli_main(["analyze", "gate", *COMMITTED_ROUNDS,
                   "--baseline", os.path.join(REPO,
                                              "perf-baseline.json")])
    assert rc == 0


def test_gate_committed_plus_injected_slowdown_fails(tmp_path, capsys):
    """ISSUE 9 acceptance: against a synthetic round with an injected
    significant slowdown the gate exits nonzero and names the metric
    with a p-value."""
    import shutil

    for p in COMMITTED_ROUNDS:
        shutil.copy(p, tmp_path / os.path.basename(p))
    r5 = load_bench_round(COMMITTED_ROUNDS[4])
    slowed = {}
    for k, v in r5.metrics.items():
        d = regress.direction_of(k)
        if d == "higher":
            slowed[k] = round(v * 0.7, 4)
        elif d == "lower":
            slowed[k] = round(v / 0.7, 4)
    slowed["metric"] = "fft1d_n2^20_complex64_gflops"
    slowed["unit"] = "GFLOP/s"
    slowed["value"] = slowed.pop("fft1d_n2^20_complex64_gflops")
    slowed["env"] = {"platform": "axon", "smoke": False}
    with open(tmp_path / "BENCH_r07.json", "w") as fh:
        json.dump(slowed, fh)
    files = sorted(str(p) for p in tmp_path.glob("BENCH_r0*.json"))
    # drop the incomparable smoke round so r07 chains onto r05
    files = [f for f in files if "r06" not in f]
    rc = cli_main(["analyze", "gate", *files])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSION" in out
    assert "n2^22_gflops" in out and "p=" in out


# ---------------------------------------------------------- records


def test_record_validation_and_fingerprint():
    fp = env_fingerprint(smoke=True, device_kind="cpu-test")
    assert fp["smoke"] is True and fp["device_kind"] == "cpu-test"
    good = {"metric": "m", "value": 1.0, "unit": "ms", "env": fp}
    assert validate_record(good) == []
    assert json.loads(dump_record(good))["metric"] == "m"
    assert validate_record({"metric": "m", "unit": "ms"})  # no value
    assert validate_record({"metric": "m", "value": True, "unit": "x"})
    assert validate_record({"metric": "m", "value": 1, "unit": "ms",
                            "env": {"platform": "cpu"}})  # env sans smoke
    with pytest.raises(ValueError):
        dump_record({"value": 1.0})


def test_bench_record_contract_still_validates():
    """The committed rounds' parsed records satisfy the emission
    schema the helpers now enforce (metric/value/unit) — the helper
    gates future records to the same contract."""
    for path in COMMITTED_ROUNDS:
        with open(path) as fh:
            parsed = json.load(fh)["parsed"]
        assert validate_record(parsed) == [], path


# -------------------------------------------------------------- CLI


def test_cli_fit_smoke(tmp_path, capsys):
    tsv = write_tsv(tmp_path / "fourier-parallel-pi-pthreads-results.tsv",
                    make_phase_rows())
    rc = cli_main(["analyze", "fit", tsv, "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    rep = json.loads(out)[tsv]
    assert rep["total"]["holds"] is True
    assert "ci95" in rep["funnel"] and "cells" in rep
    # --events: the span-derived fit through the same CLI
    ev = write_span_events(tmp_path / "ev.jsonl", make_phase_rows())
    rc = cli_main(["analyze", "fit", "--events", ev])
    assert rc == 0
    assert "law holds: Yes" in capsys.readouterr().out


def test_cli_fit_failure_exit_and_allow_fail(tmp_path, capsys):
    rng = np.random.default_rng(5)
    rows = []
    for n in (1024, 4096, 16384):
        for p in (1, 2, 4, 8):
            for _ in range(4):
                t = 5.0 * (1 + 0.05 * rng.standard_normal())
                rows.append([n, p, t, t / 2, t / 2])
    bad = write_tsv(tmp_path / "flat.tsv", rows)
    assert cli_main(["analyze", "fit", bad, "--json"]) == 1
    capsys.readouterr()
    # --allow-fail inverts: a documented violation failing is rc 0
    assert cli_main(["analyze", "fit", bad, "--allow-fail", "flat",
                     "--json"]) == 0
    capsys.readouterr()


def test_cli_report_smoke(tmp_path, capsys):
    rows = make_phase_rows()
    tsv = write_tsv(tmp_path / "sweep.tsv", rows)
    ev = write_span_events(tmp_path / "ev.jsonl", rows)
    rc = cli_main(["analyze", "report", tsv, "--events", ev,
                   "--bench", *COMMITTED_ROUNDS, "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    assert doc["by_source"] == {"tsv": 3 * len(rows),
                                "obs": 3 * len(rows), "bench": 36}
    assert len(doc["rounds"]) == 6
    assert doc["skipped_pairs"][0]["to_round"] == 6
    assert doc["comparable_pairs"] == 4
    assert "change_points" in doc
    # span- and tsv-derived shares ride side by side, agreeing
    shares = doc["phase_shares"]
    for cell, v in shares["tsv"].items():
        assert shares["obs"][cell]["funnel"] == pytest.approx(
            v["funnel"], abs=1e-6)


def test_cli_missing_inputs_are_usage_errors(tmp_path, capsys):
    """Missing/corrupt inputs answer the documented rc-2 usage error
    with an `error:` line, never a traceback."""
    assert cli_main(["analyze", "report", "--bench",
                     str(tmp_path / "nope.json")]) == 2
    assert "error:" in capsys.readouterr().err
    assert cli_main(["analyze", "fit", "--events",
                     str(tmp_path / "missing.jsonl")]) == 2
    assert "error:" in capsys.readouterr().err
    bad = write_tsv(tmp_path / "marked.tsv", make_phase_rows())
    with open(bad, "a") as fh:
        fh.write("64\t2\t1.0\t0.5\t0.5\tWHAT\n")
    assert cli_main(["analyze", "report", bad]) == 2
    assert "unknown row marker" in capsys.readouterr().err


def test_replicated_threshold_falls_back_to_scalar(tmp_path):
    """3-4 reps per side is below the normal approximation's validity
    (its exact-test floor can't reach alpha): such metrics take the
    calibrated scalar path instead."""
    paths = []
    for i, base in enumerate((1000.0, 1002.0, 998.0, 1001.0), start=1):
        paths.append(write_round(
            tmp_path / f"bench_r{i:02d}.json", i,
            {"__value__": base,
             "tput_gflops": [base - 1, base, base + 1]},
            env={"platform": "cpu", "device_kind": "test",
                 "smoke": False}))
    paths.append(write_round(
        tmp_path / "bench_r05.json", 5,
        {"__value__": 700.0, "tput_gflops": [699.0, 700.0, 701.0]},
        env={"platform": "cpu", "device_kind": "test", "smoke": False}))
    result = regress.gate_rounds(load_bench_rounds(paths))
    assert not result.ok
    assert all(r.test == "scalar-z" for r in result.new)


def test_cli_gate_json_and_usage_errors(tmp_path, capsys):
    rc = cli_main(["analyze", "gate", *COMMITTED_ROUNDS, "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    assert doc["ok"] is True
    assert len(doc["rounds"]) == 6 and doc["new"] == []
    assert doc["skipped_pairs"] and doc["change_points"]
    # a single round is not a trajectory
    assert cli_main(["analyze", "gate", COMMITTED_ROUNDS[0]]) == 2
    capsys.readouterr()
    # an unusable baseline is a usage error, not a crash
    bad = tmp_path / "bad.json"
    bad.write_text("[]")
    assert cli_main(["analyze", "gate", *COMMITTED_ROUNDS,
                     "--baseline", str(bad)]) == 2
    capsys.readouterr()


def test_cli_gate_write_baseline_roundtrip(tmp_path, capsys):
    paths = _quiet_rounds(tmp_path, count=3)
    rng = np.random.default_rng(13)
    bad = [round(float(v), 3)
           for v in 700.0 + 15.0 * rng.standard_normal(8)]
    paths.append(write_round(
        tmp_path / "bench_r04.json", 4,
        {"__value__": float(np.mean(bad)), "tput_gflops": bad},
        env={"platform": "cpu", "device_kind": "test", "smoke": False}))
    assert cli_main(["analyze", "gate", *paths]) == 1
    capsys.readouterr()
    bl = str(tmp_path / "pb.json")
    assert cli_main(["analyze", "gate", *paths,
                     "--write-baseline", bl]) == 0
    capsys.readouterr()
    assert cli_main(["analyze", "gate", *paths, "--baseline", bl]) == 0
    out = capsys.readouterr().out
    assert "accepted (baselined)" in out
