"""Tests for the flow-sensitive check layer: the CFG + analyses
(check/flow.py) independent of any rule, then every flow rule
(check/rules_flow.py — PIF302/303/304 DMA discipline, PIF112 unguarded
shared write, PIF113 await-holding-lock, PIF114 unpaired resource,
PIF115 untagged demotion) positive AND negative AND noqa AND scope,
a shipped-package-clean test per rule, and the PR-12 busy_s regression:
reverting the lock around the mesh utilization accounting must make
`pifft check` fail with PIF112.
"""

import ast
import os
import textwrap

import pytest

from cs87project_msolano2_tpu import check
from cs87project_msolano2_tpu.check import engine, flow

PKG_DIR = os.path.dirname(os.path.abspath(check.__file__))
PKG = os.path.dirname(PKG_DIR)


def fn_def(code, name=None):
    tree = ast.parse(textwrap.dedent(code))
    defs = [n for n in ast.walk(tree) if isinstance(n, flow.FN_DEFS)]
    if name is None:
        return defs[0]
    return next(d for d in defs if d.name == name)


def run(code, rule=None, path="pkg/serve/snippet.py"):
    return check.check_source(
        path, textwrap.dedent(code), rules=[rule] if rule else None)


def rule_ids(findings):
    return [f.rule for f in findings]


def call_events(cfg, open_name="open_it", close_name="close_it",
                token="r"):
    """Test vocabulary: calls named open_it/close_it become pairing
    events."""
    events = []
    for node in cfg.statement_nodes():
        for root in node.scan:
            if root is None:
                continue
            for sub in flow.shallow_walk(root):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Name):
                    if sub.func.id == open_name:
                        events.append(flow.Event("open", token,
                                                 node.idx, sub))
                    elif sub.func.id == close_name:
                        events.append(flow.Event("close", token,
                                                 node.idx, sub))
    return events


# ================================================== CFG construction


def test_cfg_if_diamond_joins():
    fn = fn_def("""
        def f(c):
            a = 1
            if c:
                b = 2
            else:
                b = 3
            return b
    """)
    cfg = flow.build_cfg(fn)
    # the return is reachable from both branch bodies
    stmts = {n.idx: n for n in cfg.statement_nodes()}
    branch_nodes = [i for i, n in stmts.items()
                    if isinstance(n.stmt, ast.Assign)
                    and n.stmt.value.value in (2, 3)]
    ret = next(i for i, n in stmts.items() if n.kind == "return")
    assert len(branch_nodes) == 2
    for b in branch_nodes:
        assert ret in cfg.reachable(b)
    assert cfg.exit in cfg.reachable(cfg.entry)


def test_cfg_while_has_back_edge():
    fn = fn_def("""
        def f(n):
            i = 0
            while i < n:
                i += 1
            return i
    """)
    cfg = flow.build_cfg(fn)
    head = next(n.idx for n in cfg.statement_nodes()
                if n.kind == "loop")
    body = next(n.idx for n in cfg.statement_nodes()
                if isinstance(n.stmt, ast.AugAssign))
    assert head in cfg.succ[body]          # the back edge
    assert body in cfg.reachable(head)
    assert cfg.exit in cfg.reachable(head)  # loop exit


def test_cfg_for_loop_can_run_zero_times():
    fn = fn_def("""
        def f(xs):
            hit = False
            for x in xs:
                hit = True
            return hit
    """)
    cfg = flow.build_cfg(fn)
    body = next(n.idx for n in cfg.statement_nodes()
                if isinstance(n.stmt, ast.Assign)
                and n.stmt.value.value is True)
    # a path around the loop body exists
    assert cfg.exit in cfg.reachable(cfg.entry, avoid=frozenset([body]))


def test_cfg_early_return_bypasses_tail():
    fn = fn_def("""
        def f(c):
            if c:
                return 1
            tail = 2
            return tail
    """)
    cfg = flow.build_cfg(fn)
    early = next(n.idx for n in cfg.statement_nodes()
                 if n.kind == "return"
                 and isinstance(n.stmt.value, ast.Constant))
    tail = next(n.idx for n in cfg.statement_nodes()
                if isinstance(n.stmt, ast.Assign))
    assert cfg.exit in cfg.succ[early]
    assert tail not in cfg.reachable(early)


def test_cfg_try_finally_runs_on_raise_path():
    fn = fn_def("""
        def f(c):
            try:
                if c:
                    raise ValueError("x")
                ok = 1
            finally:
                cleanup = True
            return ok
    """)
    cfg = flow.build_cfg(fn)
    raise_n = next(n.idx for n in cfg.statement_nodes()
                   if n.kind == "raise")
    fin = next(n.idx for n in cfg.statement_nodes()
               if isinstance(n.stmt, ast.Assign)
               and isinstance(n.stmt.targets[0], ast.Name)
               and n.stmt.targets[0].id == "cleanup")
    # the raise flows through the finally, then keeps propagating
    assert fin in cfg.reachable(raise_n)
    assert cfg.raise_exit in cfg.reachable(raise_n)


def test_cfg_except_handler_reached_from_body():
    fn = fn_def("""
        def f():
            try:
                risky = work()
            except Exception:
                handled = True
            return 0
    """)
    cfg = flow.build_cfg(fn)
    handler_body = next(n.idx for n in cfg.statement_nodes()
                        if isinstance(n.stmt, ast.Assign)
                        and n.stmt.targets[0].id == "handled")
    assert handler_body in cfg.reachable(cfg.entry)
    assert cfg.exit in cfg.reachable(handler_body)


def test_cfg_break_exits_loop_continue_reenters():
    fn = fn_def("""
        def f(xs):
            for x in xs:
                if x < 0:
                    continue
                if x > 9:
                    break
                use(x)
            return 0
    """)
    cfg = flow.build_cfg(fn)
    head = next(n.idx for n in cfg.statement_nodes()
                if n.kind == "loop")
    cont = next(n.idx for n in cfg.statement_nodes()
                if isinstance(n.stmt, ast.Continue))
    brk = next(n.idx for n in cfg.statement_nodes()
               if isinstance(n.stmt, ast.Break))
    ret = next(n.idx for n in cfg.statement_nodes()
               if n.kind == "return")
    assert head in cfg.succ[cont]
    assert ret in cfg.succ[brk]


def test_cfg_grid_back_edge_option():
    fn = fn_def("""
        def kernel(i):
            a = 1
    """)
    plain = flow.build_cfg(fn)
    grid = flow.build_cfg(fn, loop_back_edge=True)
    assert plain.entry not in plain.reachable(plain.exit)
    assert grid.entry in grid.reachable(grid.exit)


def test_cfg_inlines_when_decorated_defs_conditionally():
    fn = fn_def("""
        def kernel(i):
            before = 1

            @pl.when(i == 0)
            def _phase():
                inside = 2

            after = 3
    """, name="kernel")
    cfg = flow.build_cfg(fn, inline_decorated=("when",))
    names = {}
    for n in cfg.statement_nodes():
        if isinstance(n.stmt, ast.Assign) and \
                isinstance(n.stmt.targets[0], ast.Name):
            names[n.stmt.targets[0].id] = n.idx
    assert set(names) == {"before", "inside", "after"}
    # conditional region: `after` reachable both through and around it
    assert names["after"] in cfg.reachable(names["inside"])
    assert names["after"] in cfg.reachable(
        names["before"], avoid=frozenset([names["inside"]]))


# ================================================== pairing analysis


def test_pairing_straight_line_is_clean():
    fn = fn_def("""
        def f():
            open_it()
            close_it()
    """)
    cfg = flow.build_cfg(fn)
    res = flow.pair_events(cfg, call_events(cfg))
    assert res.leaks() == [] and res.over_closes == []


def test_pairing_open_without_close_is_must_leak():
    fn = fn_def("""
        def f():
            open_it()
    """)
    cfg = flow.build_cfg(fn)
    res = flow.pair_events(cfg, call_events(cfg))
    assert [v.must_leak for v in res.leaks()] == [True]


def test_pairing_close_in_branch_is_may_not_must():
    fn = fn_def("""
        def f(c):
            open_it()
            if c:
                close_it()
    """)
    cfg = flow.build_cfg(fn)
    res = flow.pair_events(cfg, call_events(cfg))
    leaks = res.leaks()
    assert len(leaks) == 1
    assert leaks[0].may_leak and not leaks[0].must_leak


def test_pairing_open_in_both_branches_close_after_is_clean():
    fn = fn_def("""
        def f(c):
            if c:
                open_it()
            else:
                open_it()
            close_it()
    """)
    cfg = flow.build_cfg(fn)
    res = flow.pair_events(cfg, call_events(cfg))
    assert res.leaks() == [] and res.over_closes == []


def test_pairing_double_close_on_a_path_is_over_close():
    fn = fn_def("""
        def f(c):
            open_it()
            close_it()
            if c:
                close_it()
    """)
    cfg = flow.build_cfg(fn)
    res = flow.pair_events(cfg, call_events(cfg))
    assert len(res.over_closes) == 1


def test_pairing_close_only_inside_zero_trip_loop_is_may_leak():
    fn = fn_def("""
        def f(xs):
            open_it()
            for x in xs:
                close_it()
    """)
    cfg = flow.build_cfg(fn)
    res = flow.pair_events(cfg, call_events(cfg))
    leaks = res.leaks()
    assert len(leaks) == 1 and leaks[0].may_leak \
        and not leaks[0].must_leak


def test_pairing_finally_close_covers_raise_path():
    fn = fn_def("""
        def f(c):
            open_it()
            try:
                if c:
                    raise ValueError("x")
            finally:
                close_it()
    """)
    cfg = flow.build_cfg(fn)
    res = flow.pair_events(
        cfg, call_events(cfg),
        leak_exits=(cfg.exit, cfg.raise_exit))
    assert res.leaks() == []


def test_pairing_explicit_raise_path_leaks():
    fn = fn_def("""
        def f(c):
            open_it()
            if c:
                raise ValueError("x")
            close_it()
    """)
    cfg = flow.build_cfg(fn)
    res = flow.pair_events(
        cfg, call_events(cfg),
        leak_exits=(cfg.exit, cfg.raise_exit))
    leaks = res.leaks()
    assert len(leaks) == 1 and leaks[0].may_leak


def test_pairing_open_that_throws_did_not_open():
    # the exception edge out of a try carries the state from BEFORE
    # the statement: a failing open leaves nothing to close
    fn = fn_def("""
        def f():
            try:
                open_it()
            except Exception:
                raise
            close_it()
    """)
    cfg = flow.build_cfg(fn)
    res = flow.pair_events(
        cfg, call_events(cfg),
        leak_exits=(cfg.exit, cfg.raise_exit))
    assert res.leaks() == []


# ======================================================== locksets


def test_lockset_with_region_held_only_inside():
    fn = fn_def("""
        def f(self):
            before = 1
            with self._lock:
                inside = 2
            after = 3
    """)
    cfg = flow.build_cfg(fn)
    locks = flow.flow_locksets(cfg)
    by_name = {n.stmt.targets[0].id: n.idx
               for n in cfg.statement_nodes()
               if isinstance(n.stmt, ast.Assign)}
    assert locks[by_name["before"]] == frozenset()
    assert locks[by_name["inside"]] == frozenset({"self._lock"})
    assert locks[by_name["after"]] == frozenset()


def test_lockset_nested_with_holds_both():
    fn = fn_def("""
        def f(self):
            with self._lock:
                with self._other_lock:
                    inside = 1
    """)
    cfg = flow.build_cfg(fn)
    locks = flow.flow_locksets(cfg)
    node = next(n.idx for n in cfg.statement_nodes()
                if isinstance(n.stmt, ast.Assign))
    assert locks[node] == frozenset({"self._lock", "self._other_lock"})


def test_lockset_join_is_intersection():
    # acquired on only ONE inbound path -> not held at the merge
    fn = fn_def("""
        def f(self, c):
            if c:
                self.big_lock.acquire()
            merged = 1
    """)
    cfg = flow.build_cfg(fn)
    locks = flow.flow_locksets(cfg)
    node = next(n.idx for n in cfg.statement_nodes()
                if isinstance(n.stmt, ast.Assign)
                and n.stmt.targets[0].id == "merged")
    assert locks[node] == frozenset()


def test_lockset_acquire_release_flow():
    fn = fn_def("""
        def f(self):
            self.big_lock.acquire()
            held = 1
            self.big_lock.release()
            free = 2
    """)
    cfg = flow.build_cfg(fn)
    locks = flow.flow_locksets(cfg)
    by_name = {n.stmt.targets[0].id: n.idx
               for n in cfg.statement_nodes()
               if isinstance(n.stmt, ast.Assign)}
    assert "self.big_lock" in locks[by_name["held"]]
    assert locks[by_name["free"]] == frozenset()


def test_lockset_early_return_stays_locked_until_exit():
    fn = fn_def("""
        def f(self, c):
            with self._lock:
                if c:
                    return 1
                inside = 2
            return 3
    """)
    cfg = flow.build_cfg(fn)
    locks = flow.flow_locksets(cfg)
    early = next(n.idx for n in cfg.statement_nodes()
                 if n.kind == "return"
                 and isinstance(n.stmt.value, ast.Constant)
                 and n.stmt.value.value == 1)
    assert "self._lock" in locks[early]


# ============================================ PIF302/303/304 — DMA


DMA_PATH = "pkg/ops/kernel.py"


def test_pif302_flags_unwaited_branch_start():
    found = run("""
        from jax.experimental.pallas import tpu as pltpu

        def kernel(refs, sem, cond):
            def write_dma(slot):
                return pltpu.make_async_copy(refs[0], refs[1], sem)
            write_dma(0).wait()
            if cond:
                write_dma(1).start()
    """, "PIF302", DMA_PATH)
    assert rule_ids(found) == ["PIF302"]


def test_pif302_var_bound_unwaited():
    found = run("""
        from jax.experimental.pallas import tpu as pltpu

        def kernel(refs, sem):
            dma = pltpu.make_async_copy(refs[0], refs[1], sem)
            dma.start()
    """, "PIF302", DMA_PATH)
    assert rule_ids(found) == ["PIF302"]


def test_pif302_clean_when_paired():
    found = run("""
        from jax.experimental.pallas import tpu as pltpu

        def kernel(refs, sem):
            dma = pltpu.make_async_copy(refs[0], refs[1], sem)
            dma.start()
            dma.wait()
    """, "PIF302", DMA_PATH)
    assert found == []


def test_pif302_grid_kernel_cross_step_wait_is_clean():
    # the fourstep idiom: start at step i, wait at step i+2, phases
    # selected by @pl.when — legal under grid semantics
    found = run("""
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(refs, sem, QB):
            i = pl.program_id(0)

            def write_dma(slot, blk):
                return pltpu.make_async_copy(refs[0], refs[1], sem)

            @pl.when(i < QB)
            def _phase_a():
                @pl.when(i >= 2)
                def _retire():
                    write_dma(i % 2, i - 2).wait()

                write_dma(i % 2, i).start()
    """, "PIF302", DMA_PATH)
    assert found == []


def test_pif302_grid_kernel_missing_wait_flags():
    found = run("""
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(refs, sem, QB):
            i = pl.program_id(0)

            def write_dma(slot, blk):
                return pltpu.make_async_copy(refs[0], refs[1], sem)

            @pl.when(i < QB)
            def _phase_a():
                write_dma(i % 2, i).start()
    """, "PIF302", DMA_PATH)
    assert rule_ids(found) == ["PIF302"]


def test_pif303_flags_double_wait_path():
    found = run("""
        from jax.experimental.pallas import tpu as pltpu

        def kernel(refs, sem, cond):
            dma = pltpu.make_async_copy(refs[0], refs[1], sem)
            dma.start()
            dma.wait()
            if cond:
                dma.wait()
    """, "PIF303", DMA_PATH)
    assert rule_ids(found) == ["PIF303"]


def test_pif303_clean_single_wait():
    found = run("""
        from jax.experimental.pallas import tpu as pltpu

        def kernel(refs, sem):
            dma = pltpu.make_async_copy(refs[0], refs[1], sem)
            dma.start()
            dma.wait()
    """, "PIF303", DMA_PATH)
    assert found == []


def test_pif304_flags_wait_skippable_by_branch():
    found = run("""
        from jax.experimental.pallas import tpu as pltpu

        def kernel(refs, sem, cond):
            dma = pltpu.make_async_copy(refs[0], refs[1], sem)
            dma.start()
            if cond:
                dma.wait()
    """, "PIF304", DMA_PATH)
    assert rule_ids(found) == ["PIF304"]


def test_pif304_flags_wait_in_zero_trip_loop():
    found = run("""
        from jax.experimental.pallas import tpu as pltpu

        def kernel(refs, sem, rows):
            dma = pltpu.make_async_copy(refs[0], refs[1], sem)
            dma.start()
            for r in rows:
                dma.wait()
    """, "PIF304", DMA_PATH)
    assert rule_ids(found) == ["PIF304"]


def test_pif304_clean_unconditional_wait():
    found = run("""
        from jax.experimental.pallas import tpu as pltpu

        def kernel(refs, sem, cond):
            dma = pltpu.make_async_copy(refs[0], refs[1], sem)
            dma.start()
            if cond:
                early = 1
            dma.wait()
    """, "PIF304", DMA_PATH)
    assert found == []


def test_dma_rules_scope_is_ops_only():
    code = """
        from jax.experimental.pallas import tpu as pltpu

        def kernel(refs, sem):
            pltpu.make_async_copy(refs[0], refs[1], sem).start()
    """
    assert rule_ids(run(code, "PIF302", DMA_PATH)) == ["PIF302"]
    assert run(code, "PIF302", "pkg/serve/elsewhere.py") == []


def test_dma_noqa_suppresses():
    found = run("""
        from jax.experimental.pallas import tpu as pltpu

        def kernel(refs, sem):
            dma = pltpu.make_async_copy(refs[0], refs[1], sem)
            dma.start()  # pifft: noqa[PIF302]: retired by the next kernel launch by design
    """, "PIF302", DMA_PATH)
    assert found == []


# =================================================== PIF112 — locks


def test_pif112_flags_unlocked_write_to_guarded_attr():
    found = run("""
        class Device:
            def bump(self, dt):
                with self._busy_lock:
                    self.busy_s += dt

            def skew(self):
                self.busy_s = 0.0
    """, "PIF112")
    assert rule_ids(found) == ["PIF112"]
    assert "busy_s" in found[0].message


def test_pif112_clean_when_all_writes_locked():
    found = run("""
        class Device:
            def bump(self, dt):
                with self._busy_lock:
                    self.busy_s += dt

            def reset(self):
                with self._busy_lock:
                    self.busy_s = 0.0
    """, "PIF112")
    assert found == []


def test_pif112_init_writes_exempt():
    found = run("""
        class Device:
            def __init__(self):
                self.busy_s = 0.0

            def bump(self, dt):
                with self._busy_lock:
                    self.busy_s += dt
    """, "PIF112")
    assert found == []


def test_pif112_flags_executor_thread_write_without_any_lock():
    # the regression direction: delete the lock everywhere and the
    # thread-evidence still fires
    found = run("""
        import asyncio

        class Mesh:
            async def invoke(self, device, dt):
                def execute():
                    device.busy_s += dt

                call = execute
                return await asyncio.get_running_loop() \\
                    .run_in_executor(None, call)
    """, "PIF112")
    assert rule_ids(found) == ["PIF112"]


def test_pif112_executor_write_under_lock_is_clean():
    found = run("""
        import asyncio

        class Mesh:
            async def invoke(self, device, dt):
                def execute():
                    with device._busy_lock:
                        device.busy_s += dt

                return await asyncio.get_running_loop() \\
                    .run_in_executor(None, execute)
    """, "PIF112")
    assert found == []


def test_pif112_thread_local_object_write_is_clean():
    found = run("""
        import asyncio

        class Mesh:
            async def invoke(self):
                def execute():
                    box = Box()
                    box.value = 1
                    return box

                return await asyncio.get_running_loop() \\
                    .run_in_executor(None, execute)
    """, "PIF112")
    assert found == []


def test_pif112_scope_is_serve_only():
    code = """
        class Device:
            def bump(self, dt):
                with self._busy_lock:
                    self.busy_s += dt

            def skew(self):
                self.busy_s = 0.0
    """
    assert run(code, "PIF112", "pkg/plans/core.py") == []


# ============================================ PIF113 — await + lock


def test_pif113_flags_await_under_sync_lock():
    found = run("""
        class D:
            async def flush(self):
                with self._lock:
                    await self.sink.drain()
    """, "PIF113")
    assert rule_ids(found) == ["PIF113"]


def test_pif113_async_with_lock_is_clean():
    found = run("""
        class D:
            async def flush(self):
                async with self._write_lock:
                    await self.sink.drain()
    """, "PIF113")
    assert found == []


def test_pif113_await_after_region_is_clean():
    found = run("""
        class D:
            async def flush(self):
                with self._lock:
                    snapshot = list(self.rows)
                await self.sink.send(snapshot)
    """, "PIF113")
    assert found == []


def test_pif113_scope_is_serve_only():
    code = """
        class D:
            async def flush(self):
                with self._lock:
                    await self.sink.drain()
    """
    assert run(code, "PIF113", "pkg/analyze/cli.py") == []


# ========================================== PIF114 — resource pairs


def test_pif114_flags_quota_leak_on_exception_path():
    found = run("""
        class D:
            def admit(self, tenant, bad):
                self.admission.charge(tenant, 1.0)
                if bad:
                    raise RuntimeError("boom")
                self.admission.release(tenant)
    """, "PIF114")
    assert rule_ids(found) == ["PIF114"]
    assert "quota" in found[0].message


def test_pif114_finally_release_is_clean():
    found = run("""
        class D:
            def admit(self, tenant, bad):
                self.admission.charge(tenant, 1.0)
                try:
                    if bad:
                        raise RuntimeError("boom")
                finally:
                    self.admission.release(tenant)
    """, "PIF114")
    assert found == []


def test_pif114_callback_registered_release_is_clean():
    found = run("""
        class D:
            def admit(self, req, tenant):
                self.admission.charge(tenant, 1.0)
                req.future.add_done_callback(
                    lambda _f: self.admission.release(tenant))
    """, "PIF114")
    assert found == []


def test_pif114_ownership_transfer_is_clean():
    found = run("""
        class D:
            def stage(self, bucket, width):
                xr = self.pool.acquire((bucket, width))
                xi = self.pool.acquire((bucket, width))
                return xr, xi
    """, "PIF114")
    assert found == []


def test_pif114_flags_buffer_leaked_by_early_return():
    found = run("""
        class D:
            def stage(self, bucket, width, planes):
                xr = self.pool.acquire((bucket, width))
                if not planes:
                    return None
                self.pool.release(xr)
                return None
    """, "PIF114")
    assert rule_ids(found) == ["PIF114"]


def test_pif114_open_append_with_statement_is_clean():
    found = run("""
        from cs87project_msolano2_tpu.resilience.journal import open_append

        def record(path, rec):
            with open_append(path) as fh:
                fh.write(rec)
    """, "PIF114", "pkg/resilience/j.py")
    assert found == []


def test_pif114_flags_dangling_open_append():
    found = run("""
        from cs87project_msolano2_tpu.resilience.journal import open_append

        def record(path, rec, bad):
            fh = open_append(path)
            fh.write(rec)
            if bad:
                return None
            fh.close()
            return None
    """, "PIF114", "pkg/resilience/j.py")
    assert rule_ids(found) == ["PIF114"]


def test_pif114_noqa_suppresses():
    found = run("""
        class D:
            def admit(self, tenant, bad):
                self.admission.charge(tenant, 1.0)  # pifft: noqa[PIF114]: released by the caller's teardown hook
                if bad:
                    raise RuntimeError("boom")
                self.admission.release(tenant)
    """, "PIF114")
    assert found == []


def test_pif114_scope():
    code = """
        class D:
            def admit(self, tenant):
                self.admission.charge(tenant, 1.0)
    """
    assert rule_ids(run(code, "PIF114")) == ["PIF114"]
    assert run(code, "PIF114", "pkg/models/x.py") == []


# ======================================= PIF115 — untagged demotion


def test_pif115_flags_untagged_trail_append():
    found = run("""
        def serve(outcome, rung):
            if rung is not None:
                outcome.degrade.append(f"overload:{rung}")
            return outcome
    """, "PIF115")
    assert rule_ids(found) == ["PIF115"]


def test_pif115_tag_after_append_is_clean():
    found = run("""
        def serve(outcome, rung):
            if rung is not None:
                outcome.degrade.append(f"overload:{rung}")
                outcome.degraded = True
            return outcome
    """, "PIF115")
    assert found == []


def test_pif115_tag_before_append_is_clean():
    found = run("""
        def promote(outcome, nxt):
            outcome.degraded = True
            outcome.degrade.append(f"precision:{nxt}")
            return outcome
    """, "PIF115")
    assert found == []


def test_pif115_tag_via_keyword_is_clean():
    found = run("""
        def build(trail, rung):
            trail = list(trail)
            trail.append(f"overload:{rung}")
            demotions = trail
            demotions.append("x")
            return Outcome(degraded=True, degrade=demotions)
    """, "PIF115")
    assert found == []


def test_pif115_raise_path_needs_no_tag():
    # the value never escapes on a raise path
    found = run("""
        def serve(outcome, rung):
            outcome.degrade.append(f"overload:{rung}")
            raise RuntimeError("batch failed anyway")
    """, "PIF115")
    assert found == []


def test_pif115_flags_untagged_rung_call():
    found = run("""
        from cs87project_msolano2_tpu.resilience.degrade import promote_precision

        def enforce(plan, err, budget):
            nxt = promote_precision(plan, err, budget)
            return nxt
    """, "PIF115")
    assert rule_ids(found) == ["PIF115"]


def test_pif115_degrade_module_exempt():
    code = """
        def note(plan, record):
            plan.demotions.append(record)
            return plan
    """
    pkg_path = os.path.join(PKG, "resilience", "degrade.py")
    assert check.check_source(pkg_path, textwrap.dedent(code),
                              rules=["PIF115"]) == []
    assert rule_ids(run(code, "PIF115",
                        "pkg/resilience/retry.py")) == ["PIF115"]


def test_pif115_noqa_suppresses():
    found = run("""
        def serve(outcome, rung):
            outcome.degrade.append(f"overload:{rung}")  # pifft: noqa[PIF115]: tagged by the dispatcher at delivery
            return outcome
    """, "PIF115")
    assert found == []


# ==================================== shipped-package-clean capstones


@pytest.mark.parametrize("rule, paths", [
    ("PIF302", ("ops",)),
    ("PIF303", ("ops",)),
    ("PIF304", ("ops",)),
    ("PIF112", ("serve",)),
    ("PIF113", ("serve",)),
    ("PIF114", ("serve", "resilience", "obs")),
    ("PIF115", ("serve", "resilience", "plans", "parallel")),
])
def test_shipped_package_clean(rule, paths):
    targets = [os.path.join(PKG, p) for p in paths]
    found = check.check_paths(targets, rules=[rule])
    assert found == [], engine.format_human(found)


# ======================================= the PR-12 busy_s regression


MESH_PATH = os.path.join(PKG, "serve", "mesh.py")
LOCKED = """                with device._busy_lock:
                    device.busy_s += dt"""
UNLOCKED = """                device.busy_s += dt"""


def test_mesh_busy_s_lock_revert_fails_pif112():
    """Reverting the PR-12 lock around the utilization accounting must
    make `pifft check` fail with PIF112 — the race class is now a
    machine-checked invariant, not review prose."""
    src = open(MESH_PATH, encoding="utf-8").read()
    assert LOCKED in src, "mesh.py busy_s accounting moved; update test"
    reverted = src.replace(LOCKED, UNLOCKED)
    found = check.check_source(MESH_PATH, reverted, rules=["PIF112"])
    assert "PIF112" in rule_ids(found), \
        "unlocked busy_s += must fail PIF112"
    assert any("busy_s" in f.message for f in found)


def test_mesh_as_shipped_is_pif112_clean():
    src = open(MESH_PATH, encoding="utf-8").read()
    assert check.check_source(MESH_PATH, src, rules=["PIF112"]) == []


# =========================================== registry / docs parity


def test_flow_rules_registered_with_metadata():
    rules = check.all_rules()
    for rid in ("PIF302", "PIF303", "PIF304", "PIF112", "PIF113",
                "PIF114", "PIF115"):
        assert rid in rules
        r = rules[rid]
        assert r.name and r.summary and r.invariant


# ================================ review-hardening regression tests


def test_pif113_explicit_asyncio_acquire_is_clean():
    """`await lock.acquire()` is an asyncio.Lock — the sanctioned
    kind; only a BARE (sync) acquire counts as holding a threading
    lock across an await."""
    found = run("""
        class D:
            async def flush(self):
                await self._lock.acquire()
                try:
                    await self.sink.drain()
                finally:
                    self._lock.release()
    """, "PIF113")
    assert found == []


def test_pif112_same_attr_name_on_unrelated_class_is_clean():
    """Lock-guarded `self.count` on one class must not indict a
    same-named attribute on an unrelated class in the same file."""
    found = run("""
        class A:
            def read(self):
                with self._lock:
                    return self.count

        class B:
            def reset(self):
                self.count = 0
    """, "PIF112")
    assert found == []


def test_pif112_unknown_receiver_still_matches_guarded_attr():
    """The busy_s shape: the locked access uses a non-self receiver
    (its class is statically unknown), so a bare write to the same
    attribute anywhere in the file still flags."""
    found = run("""
        class Mesh:
            def bump(self, device, dt):
                with device._busy_lock:
                    device.busy_s += dt

            def skew(self, device):
                device.busy_s = 0.0
    """, "PIF112")
    assert rule_ids(found) == ["PIF112"]


# ===================================================================
# The interprocedural layer: PIF118-PIF121 (check/taint.py) — per
# rule: positive, negative-via-sanitizer, cross-file two-hop, noqa,
# scope.  Cross-file cases go through check.check_sources, which runs
# several in-memory files as ONE program.


def run_prog(sources, rule, report=None):
    return check.check_sources(
        {p: textwrap.dedent(s) for p, s in sources.items()},
        rules=[rule], report=report)


# ================================ PIF118 — untrusted size to sink


def test_pif118_wire_width_to_frombuffer_count():
    found = run("""
        import numpy as np

        def land(frame, buf):
            return np.frombuffer(buf, np.float32, count=frame.width)
    """, "PIF118")
    assert rule_ids(found) == ["PIF118"]
    (f,) = found
    assert "width" in f.message and "frombuffer" in f.message
    # the finding carries the source->sink path for codeFlows
    assert len(f.flow) >= 2
    assert "count/offset" in f.flow[-1][2]


def test_pif118_wire_n_to_allocation():
    found = run("""
        def stage(ack):
            return bytearray(ack.n)
    """, "PIF118")
    assert rule_ids(found) == ["PIF118"]
    assert "allocation" in found[0].message


def test_pif118_wire_slot_to_ring_index():
    found = run("""
        def view(ring, frame):
            return ring[frame.slot]
    """, "PIF118")
    assert rule_ids(found) == ["PIF118"]
    assert "index" in found[0].message


def test_pif118_bounds_check_sanitizes():
    found = run("""
        import numpy as np

        MAX_WIDTH = 1 << 20

        def land(frame, buf):
            width = frame.width
            if width > MAX_WIDTH:
                raise ValueError("width out of contract")
            return np.frombuffer(buf, np.float32, count=width)
    """, "PIF118")
    assert found == []


def test_pif118_range_guard_sanitizes_index():
    found = run("""
        def view(ring, frame):
            slot = frame.slot
            if not 0 <= slot < len(ring):
                raise ValueError("slot out of range")
            return ring[slot]
    """, "PIF118")
    assert found == []


def test_pif118_cross_file_return_of_wire_field():
    # the source is read in one file, spent in another: the callee
    # returns frame.n, the caller sizes an array with it
    found = run_prog({
        "pkg/serve/decode.py": """
            def read_n(frame):
                return frame.n
        """,
        "pkg/serve/handler.py": """
            import numpy as np

            from pkg.serve.decode import read_n

            def admit(frame):
                n = read_n(frame)
                return np.zeros(n)
        """,
    }, "PIF118")
    assert rule_ids(found) == ["PIF118"]
    (f,) = found
    # anchored at the untrusted READ (the natural fix/noqa site); the
    # flow walks into the caller that spends it
    assert f.path == "pkg/serve/decode.py"
    assert any(step[0] == "pkg/serve/handler.py" for step in f.flow)
    assert f.flow[-1][0] == "pkg/serve/handler.py"


def test_pif118_cross_file_taint_passed_to_callee_sink():
    # the other direction: the caller reads the field and passes it to
    # a callee whose body allocates
    found = run_prog({
        "pkg/serve/recv.py": """
            from pkg.serve.alloc import stage

            def on_frame(frame):
                return stage(frame.width)
        """,
        "pkg/serve/alloc.py": """
            import numpy as np

            def stage(width):
                return np.zeros(width)
        """,
    }, "PIF118")
    assert rule_ids(found) == ["PIF118"]
    (f,) = found
    assert f.path == "pkg/serve/recv.py"
    assert "across 1 call(s)" in f.message
    assert any(step[0] == "pkg/serve/alloc.py" for step in f.flow)


def test_pif118_decoder_bounds_check_trusts_field_programwide():
    # a decode-boundary function (*_decode) that bounds-checks `width`
    # promotes the field to trusted everywhere — the parse_header
    # contract
    sources = {
        "pkg/serve/user.py": """
            import numpy as np

            def land(frame, buf):
                return np.frombuffer(buf, np.float32,
                                     count=frame.width)
        """,
    }
    assert rule_ids(run_prog(sources, "PIF118")) == ["PIF118"]
    sources["pkg/serve/codec.py"] = """
        MAX_WIDTH = 4096

        def header_decode(buf, frame):
            width = frame.width
            if width > MAX_WIDTH:
                raise ValueError("width out of contract")
            return width
    """
    assert run_prog(sources, "PIF118") == []


def test_pif118_noqa_suppresses():
    found = run("""
        import numpy as np

        def land(frame, buf):
            w = frame.width  # pifft: noqa[PIF118]: smoke fixture, buf is trusted test data
            return np.frombuffer(buf, np.float32, count=w)
    """, "PIF118")
    assert found == []


def test_pif118_scope_is_serve_only():
    code = """
        def stage(ack):
            return bytearray(ack.n)
    """
    assert run(code, "PIF118", "pkg/analyze/snippet.py") == []


# ================================ PIF119 — unvalidated shape to plan


def test_pif119_request_field_to_plan_for():
    found = run("""
        def admit(msg):
            n = msg.get("n")
            return plan_for(n)
    """, "PIF119")
    assert rule_ids(found) == ["PIF119"]
    assert "plan construction" in found[0].message


def test_pif119_vocab_clamp_sanitizes():
    found = run("""
        def admit(msg, vocab):
            n = vocab.clamp(msg.get("n"))
            return plan_for(n)
    """, "PIF119")
    assert found == []


def test_pif119_max_cap_comparison_sanitizes():
    found = run("""
        MAX_N = 1 << 22

        def admit(msg):
            n = int(msg.get("n"))
            if n > MAX_N:
                raise ValueError("n out of contract")
            return plan_for(n)
    """, "PIF119")
    assert found == []


def test_pif119_cross_file_two_hop():
    found = run_prog({
        "pkg/serve/front.py": """
            def parse_req(msg):
                return msg.get("n")
        """,
        "pkg/plans/admit.py": """
            from pkg.serve.front import parse_req

            def plan_req(msg):
                n = parse_req(msg)
                return plan_for(n)
        """,
    }, "PIF119")
    assert rule_ids(found) == ["PIF119"]
    (f,) = found
    # anchored at the request-field read; the sink is in the caller
    assert f.path == "pkg/serve/front.py"
    assert f.flow[-1][0] == "pkg/plans/admit.py"


def test_pif119_noqa_suppresses():
    found = run("""
        def admit(msg):
            n = msg.get("n")  # pifft: noqa[PIF119]: dispatcher re-validates against the vocabulary
            return plan_for(n)
    """, "PIF119")
    assert found == []


def test_pif119_scope_excludes_ops():
    code = """
        def admit(msg):
            n = msg.get("n")
            return plan_for(n)
    """
    assert run(code, "PIF119", "pkg/ops/snippet.py") == []


# ====================== PIF120 — lock held across blocking callee


def test_pif120_sleeping_callee_under_lock():
    found = run("""
        import time

        def drain(q):
            time.sleep(0.05)

        def pump(q, state_lock):
            with state_lock:
                drain(q)
    """, "PIF120")
    assert rule_ids(found) == ["PIF120"]
    (f,) = found
    assert "state_lock" in f.message and "time.sleep" in f.message
    assert len(f.flow) >= 2


def test_pif120_call_outside_region_is_clean():
    found = run("""
        import time

        def drain(q):
            time.sleep(0.05)

        def pump(q, state_lock):
            with state_lock:
                q.append(1)
            drain(q)
    """, "PIF120")
    assert found == []


def test_pif120_nonblocking_callee_is_clean():
    found = run("""
        def drain(q):
            q.clear()

        def pump(q, state_lock):
            with state_lock:
                drain(q)
    """, "PIF120")
    assert found == []


def test_pif120_cross_file_transitive_blocking():
    found = run_prog({
        "pkg/serve/loop.py": """
            from pkg.serve.util import settle

            def pump(q, state_lock):
                with state_lock:
                    settle(q)
        """,
        "pkg/serve/util.py": """
            import time

            def settle(q):
                flush(q)

            def flush(q):
                time.sleep(0.01)
        """,
    }, "PIF120")
    assert rule_ids(found) == ["PIF120"]
    (f,) = found
    assert f.path == "pkg/serve/loop.py"
    # the path walks settle -> flush -> time.sleep
    assert sum(1 for step in f.flow
               if step[0] == "pkg/serve/util.py") >= 2


def test_pif120_noqa_suppresses():
    found = run("""
        import time

        def drain(q):
            time.sleep(0.05)

        def pump(q, state_lock):
            with state_lock:
                drain(q)  # pifft: noqa[PIF120]: startup-only path, nothing contends yet
    """, "PIF120")
    assert found == []


def test_pif120_scope_excludes_ops():
    code = """
        import time

        def drain(q):
            time.sleep(0.05)

        def pump(q, state_lock):
            with state_lock:
                drain(q)
    """
    assert run(code, "PIF120", "pkg/ops/snippet.py") == []


# ==================== PIF121 — degrade tag dropped across a call


def test_pif121_untagged_demoting_callee():
    found = run("""
        def note_overload(outcome, rung):
            outcome.degrade.append(f"overload:{rung}")
            return outcome

        def serve(outcome, rung):
            out = note_overload(outcome, rung)
            return out
    """, "PIF121")
    assert rule_ids(found) == ["PIF121"]
    (f,) = found
    assert "note_overload" in f.message
    assert len(f.flow) >= 2


def test_pif121_caller_tag_after_call_is_clean():
    found = run("""
        def note_overload(outcome, rung):
            outcome.degrade.append(f"overload:{rung}")
            return outcome

        def serve(outcome, rung):
            out = note_overload(outcome, rung)
            out.degraded = True
            return out
    """, "PIF121")
    assert found == []


def test_pif121_callee_tags_internally_is_clean():
    found = run("""
        def note_overload(outcome, rung):
            outcome.degrade.append(f"overload:{rung}")
            outcome.degraded = True
            return outcome

        def serve(outcome, rung):
            return note_overload(outcome, rung)
    """, "PIF121")
    assert found == []


def test_pif121_cross_file_demotion():
    found = run_prog({
        "pkg/resilience/retry.py": """
            def note(outcome, rung):
                outcome.degrade.append(f"overload:{rung}")
                return outcome
        """,
        "pkg/serve/front.py": """
            from pkg.resilience.retry import note

            def serve(outcome, rung):
                return note(outcome, rung)
        """,
    }, "PIF121", report=["pkg/serve/front.py"])
    assert rule_ids(found) == ["PIF121"]
    (f,) = found
    assert f.path == "pkg/serve/front.py"
    assert any(step[0] == "pkg/resilience/retry.py" for step in f.flow)


def test_pif121_degrade_engine_exempt():
    # the resilience engine itself demotes for a living; calls into it
    # do not indict the caller via THIS rule (PIF115 owns rung calls)
    found = run_prog({
        "pkg/resilience/degrade.py": """
            def note(outcome, rung):
                outcome.degrade.append(f"overload:{rung}")
                return outcome
        """,
        "pkg/serve/front.py": """
            from pkg.resilience.degrade import note

            def serve(outcome, rung):
                return note(outcome, rung)
        """,
    }, "PIF121", report=["pkg/serve/front.py"])
    assert found == []


def test_pif121_noqa_suppresses():
    found = run("""
        def note_overload(outcome, rung):
            outcome.degrade.append(f"overload:{rung}")
            return outcome

        def serve(outcome, rung):
            out = note_overload(outcome, rung)  # pifft: noqa[PIF121]: dispatcher tags at delivery
            return out
    """, "PIF121")
    assert found == []


def test_pif121_scope_excludes_analyze():
    code = """
        def note_overload(outcome, rung):
            outcome.degrade.append(f"overload:{rung}")
            return outcome

        def serve(outcome, rung):
            return note_overload(outcome, rung)
    """
    assert run(code, "PIF121", "pkg/analyze/snippet.py") == []


# ------------------------------- interprocedural shipped-clean gate


def test_shipped_package_clean_interprocedural():
    found = check.check_paths(
        [PKG], rules=["PIF118", "PIF119", "PIF120", "PIF121"])
    assert found == [], engine.format_human(found)
