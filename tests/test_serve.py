"""Tests for the serve/ subsystem (docs/SERVING.md): shape-set parsing
and warm, buffer pooling, coalescing (k concurrent requests -> fewer
kernel invocations, every row still correct), bounded-queue
backpressure (structured QueueFull, never a hang), the admission-time
and fault-driven degradation ladders (every demotion tagged
``degraded: true`` on the response and mirrored in the event stream —
the chaos satellite), the wire protocol, the open-loop load generator,
and the ``pifft serve --smoke`` / ``bench.py --serve-load`` entry
points end to end on CPU."""

import asyncio
import json

import numpy as np
import pytest

from cs87project_msolano2_tpu import obs, resilience
from cs87project_msolano2_tpu.serve import (
    BufferPool,
    Dispatcher,
    DispatcherClosed,
    QueueFull,
    ServeConfig,
    ServeError,
    ShapeNotServed,
    ShapeSpec,
    batch_bucket,
    load_shapes,
    percentile,
)
from cs87project_msolano2_tpu.utils.verify import (
    pi_layout_to_natural,
    rel_err,
)

N = 256


def planes(n=N, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(n).astype(np.float32),
            rng.standard_normal(n).astype(np.float32))


def ref_fft(xr, xi):
    return np.fft.fft(xr.astype(np.complex128) + 1j * xi.astype(np.complex128))


def run_async(coro, timeout_s=120.0):
    """Every async test runs under a hard deadline: a serving-path bug
    must FAIL, never hang the suite."""

    async def bounded():
        return await asyncio.wait_for(coro, timeout=timeout_s)

    return asyncio.run(bounded())


@pytest.fixture
def obs_run():
    obs.enable()
    yield obs
    obs.disable()


# ------------------------------------------------------------- shapes


def test_shape_spec_parsing_and_labels(tmp_path):
    p = tmp_path / "shapes.jsonl"
    p.write_text('{"n": 1024}\n'
                 "# a comment line\n"
                 "\n"
                 '{"n": 2048, "layout": "pi", "precision": "fp32"}\n'
                 '{"n": 1024}\n'  # duplicate: warmed once
                 '{"n": 512, "batch": [4]}\n')
    specs = load_shapes(str(p))
    assert [s.n for s in specs] == [1024, 2048, 512]
    assert specs[1].layout == "pi" and specs[1].precision == "fp32"
    assert specs[2].batch == (4,)
    assert specs[2].label() == "4x512:natural:split3"
    assert specs[0].key().n == 1024


def test_load_shapes_rejects_bad_records(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text('{"n": 1024}\n{"batch": [2]}\n')
    with pytest.raises(ValueError, match="line 2|bad.jsonl:2"):
        load_shapes(str(p))
    p.write_text("# only comments\n")
    with pytest.raises(ValueError, match="no shapes"):
        load_shapes(str(p))
    # any n >= 2 under the cap is admissible now (docs/PLANS.md,
    # "Arbitrary n") — only degenerate n and pi non-pow2 are refused
    assert ShapeSpec(n=1000).n == 1000
    with pytest.raises(ValueError, match="2 <= n"):
        ShapeSpec(n=1)
    with pytest.raises(ValueError, match="power-of-two"):
        ShapeSpec(n=1000, layout="pi")


def test_dispatcher_warm_memoizes_plans():
    from cs87project_msolano2_tpu import plans

    spec = ShapeSpec(n=N)
    d = Dispatcher(ServeConfig(), [spec])
    warmed = d.warm()
    assert len(warmed) == 1
    hit = plans.cache.lookup(spec.key())
    assert hit is not None and hit.variant == warmed[0].variant


# ------------------------------------------------- buffers and buckets


def test_batch_bucket_powers_of_two():
    assert [batch_bucket(s) for s in (1, 2, 3, 5, 8, 9)] == \
        [1, 2, 4, 8, 8, 16]


def test_buffer_pool_reuses_staging_planes():
    pool = BufferPool(max_per_key=2)
    a = pool.acquire((4, 64))
    b = pool.acquire((4, 64))
    pool.release(a, b)
    c = pool.acquire((4, 64))
    assert c is a or c is b
    stats = pool.stats()
    assert stats["hits"] == 1 and stats["misses"] == 2
    # a different shape never aliases
    d = pool.acquire((2, 64))
    assert d.shape == (2, 64)


def test_percentile_nearest_rank():
    vals = [4.0, 1.0, 3.0, 2.0]
    assert percentile(vals, 50) == 2.0
    assert percentile(vals, 99) == 4.0
    assert percentile([7.0], 99) == 7.0
    with pytest.raises(ValueError):
        percentile([], 50)


# -------------------------------------------------- correctness paths


def test_single_request_matches_numpy():
    xr, xi = planes(seed=1)

    async def main():
        async with Dispatcher() as d:
            return await d.submit(xr, xi)

    resp = run_async(main())
    assert not resp.degraded
    assert resp.queue_wait_ms >= 0 and resp.compute_ms > 0
    assert rel_err(np.asarray(resp.yr) + 1j * np.asarray(resp.yi),
                   ref_fft(xr, xi)) < 1e-4


def test_inverse_and_pi_layout_requests():
    xr, xi = planes(seed=2)
    ref = ref_fft(xr, xi)

    async def main():
        async with Dispatcher() as d:
            fwd_pi = await d.submit(xr, xi, layout="pi")
            inv = await d.submit(
                np.real(ref).astype(np.float32),
                np.imag(ref).astype(np.float32), inverse=True)
            return fwd_pi, inv

    fwd_pi, inv = run_async(main())
    nat = pi_layout_to_natural(np.asarray(fwd_pi.yr)
                               + 1j * np.asarray(fwd_pi.yi))
    assert rel_err(nat, ref) < 1e-4
    back = np.asarray(inv.yr) + 1j * np.asarray(inv.yi)
    assert rel_err(back, (xr + 1j * xi).astype(np.complex128)) < 1e-4


def test_submit_validates_requests():
    async def main():
        async with Dispatcher() as d:
            # n=100 is a served any-length plan now; only degenerate
            # n < 2 (and over-cap) is refused at admission
            with pytest.raises(ServeError, match="2 <= n"):
                await d.submit(np.zeros(1, np.float32),
                               np.zeros(1, np.float32))
            with pytest.raises(ServeError, match="1-D"):
                await d.submit(np.zeros((2, 64), np.float32),
                               np.zeros((2, 64), np.float32))
            with pytest.raises(ServeError, match="natural"):
                await d.submit(*planes(), layout="pi", inverse=True)

    run_async(main())


def test_strict_shapes_rejects_unwarmed():
    async def main():
        cfg = ServeConfig(strict_shapes=True)
        async with Dispatcher(cfg, [ShapeSpec(n=N)]) as d:
            await d.submit(*planes())  # served
            with pytest.raises(ShapeNotServed):
                await d.submit(*planes(n=2 * N))

    run_async(main())


def test_close_races_concurrent_submits_no_orphans_no_hang():
    """The shutdown-under-load contract: close() racing a burst of
    concurrent submits serves everything admitted before the close,
    gives late submits a structured DispatcherClosed, joins every
    worker, and leaves NO orphaned future (the run_async deadline is
    the no-hang proof)."""
    xr, xi = planes()

    async def main():
        d = Dispatcher(ServeConfig(max_wait_ms=25.0, queue_depth=256))

        async def client():
            try:
                return ("ok", await d.submit(xr, xi))
            except (DispatcherClosed, QueueFull) as e:
                return ("rejected", e)

        tasks = [asyncio.ensure_future(client()) for _ in range(24)]
        await asyncio.sleep(0)  # submits enqueue before the close
        await d.close()
        with pytest.raises(DispatcherClosed):
            await d.submit(xr, xi)
        outcomes = await asyncio.gather(*tasks)
        return d, outcomes

    d, outcomes = run_async(main())
    # every future resolved, and everything admitted pre-close was
    # SERVED (the close drains, it does not drop)
    assert len(outcomes) == 24
    served = [r for tag, r in outcomes if tag == "ok"]
    assert len(served) == 24, [tag for tag, _ in outcomes]
    ref = ref_fft(xr, xi)
    got = np.asarray(served[0].yr) + 1j * np.asarray(served[0].yi)
    assert rel_err(got, ref) < 1e-4
    assert all(w.done() for w in d._workers.values())
    assert all(q.empty() for q in d._queues.values())


def test_drain_alias_serves_then_stops():
    xr, xi = planes()

    async def main():
        d = Dispatcher(ServeConfig(max_wait_ms=5.0))
        pending = [asyncio.ensure_future(d.submit(xr, xi))
                   for _ in range(3)]
        await asyncio.sleep(0)
        await d.drain()
        done = await asyncio.gather(*pending)
        with pytest.raises(DispatcherClosed):
            await d.submit(xr, xi)
        return done

    done = run_async(main())
    assert len(done) == 3 and all(r.batch_size >= 1 for r in done)


def test_submit_after_close_raises():
    async def main():
        d = Dispatcher()
        async with d:
            await d.submit(*planes())
        with pytest.raises(DispatcherClosed):
            await d.submit(*planes())

    run_async(main())


# ---------------------------------------------------------- coalescing


def test_concurrent_requests_coalesce_and_rows_stay_per_request():
    """The tentpole acceptance shape: k concurrent same-shape requests
    are served by strictly fewer kernel invocations than k, and every
    response carries ITS OWN transform (a padded coalesced batch that
    hands back the wrong rows would pass any latency assertion)."""
    k = 9
    inputs = [planes(seed=10 + i) for i in range(k)]

    async def main():
        cfg = ServeConfig(max_batch=8, max_wait_ms=50.0)
        async with Dispatcher(cfg) as d:
            resps = await asyncio.gather(
                *(d.submit(xr, xi) for xr, xi in inputs))
            return d, resps

    d, resps = run_async(main())
    label = f"{N}:natural:split3"
    row = d.stats.summary()[label]
    assert row["requests"] == k
    assert 0 < row["batches"] < k, row  # coalescing happened
    assert {r.batch_size for r in resps} <= {1, 2, 4, 8}
    for (xr, xi), resp in zip(inputs, resps):
        assert rel_err(np.asarray(resp.yr) + 1j * np.asarray(resp.yi),
                       ref_fft(xr, xi)) < 1e-4
    assert row["queue_p99_ms"] >= row["queue_p50_ms"] >= 0
    assert row["compute_p99_ms"] > 0


def test_mixed_shapes_group_separately():
    async def main():
        cfg = ServeConfig(max_wait_ms=25.0)
        async with Dispatcher(cfg) as d:
            a = planes(n=N, seed=3)
            b = planes(n=2 * N, seed=4)
            ra, rb = await asyncio.gather(d.submit(*a), d.submit(*b))
            return d, (a, ra), (b, rb)

    d, (a, ra), (b, rb) = run_async(main())
    assert rel_err(np.asarray(ra.yr) + 1j * np.asarray(ra.yi),
                   ref_fft(*a)) < 1e-4
    assert rel_err(np.asarray(rb.yr) + 1j * np.asarray(rb.yi),
                   ref_fft(*b)) < 1e-4
    summary = d.stats.summary()
    assert summary[f"{N}:natural:split3"]["requests"] == 1
    assert summary[f"{2 * N}:natural:split3"]["requests"] == 1


# ------------------------------------------------- backpressure / chaos


def test_saturated_queue_returns_structured_backpressure():
    """Past queue_depth admissions fail IMMEDIATELY with QueueFull
    carrying retry_after_ms — bounded queues reject, they never grow
    or hang (the whole run is under a hard deadline via run_async)."""
    k, depth = 12, 4

    async def main():
        cfg = ServeConfig(queue_depth=depth, max_batch=2,
                          max_wait_ms=5.0)
        async with Dispatcher(cfg) as d:
            return await asyncio.gather(
                *(d.submit(*planes(seed=i)) for i in range(k)),
                return_exceptions=True)

    results = run_async(main())
    rejected = [r for r in results if isinstance(r, QueueFull)]
    served = [r for r in results if not isinstance(r, Exception)]
    assert len(served) + len(rejected) == k
    assert served and rejected  # both outcomes occurred
    rec = rejected[0].to_record()
    assert rec["type"] == "queue_full"
    assert rec["retry_after_ms"] >= 1.0


def test_chaos_injected_fault_degrades_and_tags_every_response(obs_run):
    """The chaos satellite: under PIFFT_FAULT=serve:capacity the tuned
    path dies, the batch falls to the jnp-fft rung, every response is
    tagged degraded:true with the demotion trail, the event stream
    carries serve_degrade, and results stay correct."""
    from cs87project_msolano2_tpu.obs import events as obs_events

    inputs = [planes(seed=20 + i) for i in range(4)]

    async def main():
        with resilience.inject("serve", "capacity"):
            async with Dispatcher(ServeConfig(max_wait_ms=25.0)) as d:
                return await asyncio.gather(
                    *(d.submit(xr, xi) for xr, xi in inputs))

    resps = run_async(main())
    for (xr, xi), r in zip(inputs, resps):
        assert r.degraded is True
        assert any(tag.startswith("fault:capacity:") for tag in r.degrade)
        assert rel_err(np.asarray(r.yr) + 1j * np.asarray(r.yi),
                       ref_fft(xr, xi)) < 1e-4
    recs = obs_events.snapshot()
    kinds = {r["kind"] for r in recs}
    assert "serve_degrade" in kinds and "serve_request" in kinds
    req_events = [r for r in recs if r["kind"] == "serve_request"]
    assert all(r["payload"]["degraded"] for r in req_events)
    assert all(not obs_events.validate_event(r) for r in recs)


def test_chaos_saturation_under_injection_never_hangs(obs_run):
    """Saturation AND injected faults together: every admission still
    resolves — served (degraded) or rejected (structured QueueFull) —
    within the deadline.  No future is left pending."""
    k, depth = 10, 3

    async def main():
        cfg = ServeConfig(queue_depth=depth, max_batch=2,
                          max_wait_ms=2.0)
        with resilience.inject("serve", "capacity"):
            async with Dispatcher(cfg) as d:
                return await asyncio.gather(
                    *(d.submit(*planes(seed=30 + i)) for i in range(k)),
                    return_exceptions=True)

    results = run_async(main(), timeout_s=90.0)
    assert len(results) == k
    for r in results:
        assert isinstance(r, QueueFull) or not isinstance(r, Exception)
    served = [r for r in results if not isinstance(r, Exception)]
    assert served and all(r.degraded for r in served)


def test_degraded_rungs_preserve_inverse_direction():
    """Regression: an inverse group served through a degradation rung
    (overload mode or a fault fallback) must still compute the
    INVERSE — a fallback that quietly returned the forward transform
    would be a wrong answer tagged merely degraded."""
    from cs87project_msolano2_tpu.serve.batcher import BatchRunner, GroupKey

    xr, xi = planes(seed=50)
    ref = np.fft.ifft(xr.astype(np.complex128)
                      + 1j * xi.astype(np.complex128))
    # the dispatcher's forced-rung (overload) path, via the runner
    out = BatchRunner().run(GroupKey(n=N, inverse=True), [(xr, xi)],
                            rung="jnp-fft")
    assert rel_err(out.yr[0] + 1j * out.yi[0], ref) < 1e-4
    # the fault-fallback path, end to end
    async def main():
        with resilience.inject("serve", "capacity"):
            async with Dispatcher() as d:
                return await d.submit(xr, xi, inverse=True)

    r = run_async(main())
    assert r.degraded
    assert rel_err(np.asarray(r.yr) + 1j * np.asarray(r.yi), ref) < 1e-4


def test_transient_injection_is_retried_not_degraded():
    xr, xi = planes(seed=5)

    async def main():
        with resilience.inject("serve", "transient", count=1) as spec:
            async with Dispatcher() as d:
                r = await d.submit(xr, xi)
            return spec.fired, r

    fired, resp = run_async(main())
    assert fired == 1
    assert resp.degraded is False and resp.degrade == []
    assert rel_err(np.asarray(resp.yr) + 1j * np.asarray(resp.yi),
                   ref_fft(xr, xi)) < 1e-4


def test_admission_overload_serves_cheap_rung_tagged():
    """A near-full queue flips the worker into overload mode: the
    batch skips the tuned kernel for the jnp-fft rung and every
    response says so (admission-time graceful degradation)."""
    depth = 8

    async def main():
        cfg = ServeConfig(queue_depth=depth, max_batch=depth,
                          max_wait_ms=5.0,
                          overload_watermark=0.8)
        async with Dispatcher(cfg) as d:
            return await asyncio.gather(
                *(d.submit(*planes(seed=40 + i)) for i in range(depth)))

    resps = run_async(main())
    # all enqueued before the worker first drained: fill was (depth-1)/
    # depth >= the watermark, so the FIRST batch ran overloaded
    overloaded = [r for r in resps
                  if any(t.startswith("overload:") for t in r.degrade)]
    assert overloaded and all(r.degraded for r in overloaded)


# ----------------------------------------------------------- protocol


def test_protocol_frame_roundtrip_and_socket_server():
    from cs87project_msolano2_tpu.serve import protocol

    obj = {"op": "fft", "id": 3, "xr": [0.0, 1.0]}
    frame = protocol.encode_frame(obj)
    assert frame[:4] == len(frame[4:]).to_bytes(4, "big")
    assert json.loads(frame[4:].decode()) == obj

    xr, xi = planes(seed=6)

    async def main():
        async with Dispatcher(ServeConfig(max_wait_ms=5.0)) as d:
            server = await asyncio.start_server(
                lambda r, w: protocol.handle_connection(d, r, w),
                "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            async with server:
                reply = await protocol.request_over_socket(
                    "127.0.0.1", port, xr, xi)
                # unknown ops answer structured errors, same connection
                # discipline
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port)
                writer.write(protocol.encode_frame({"op": "nope",
                                                    "id": 9}))
                await writer.drain()
                bad = await protocol.read_frame(reader)
                writer.close()
            return reply, bad

    reply, bad = run_async(main())
    assert reply["ok"] is True and reply["degraded"] is False
    got = np.asarray(reply["yr"]) + 1j * np.asarray(reply["yi"])
    assert rel_err(got, ref_fft(xr, xi)) < 1e-4
    assert reply["batch_size"] >= 1 and reply["compute_ms"] > 0
    assert bad["ok"] is False and bad["error"]["type"] == "bad_request"
    assert bad["id"] == 9


def test_protocol_client_disconnect_mid_write_never_escapes(obs_run):
    """A client vanishing mid-write (ConnectionResetError out of
    drain()) must close THAT connection with a warn event — never
    propagate into the accept loop (the satellite contract)."""
    from cs87project_msolano2_tpu.serve.protocol import (
        encode_frame,
        handle_connection,
    )

    class FakeReader:
        def __init__(self, frames):
            self._data = b"".join(encode_frame(f) for f in frames)
            self._pos = 0

        async def readexactly(self, k):
            if self._pos + k > len(self._data):
                raise asyncio.IncompleteReadError(
                    self._data[self._pos:], k)
            chunk = self._data[self._pos:self._pos + k]
            self._pos += k
            return chunk

    class DyingWriter:
        """Accepts the write, dies on drain — the kernel buffer
        accepted the bytes but the peer reset underneath."""

        def __init__(self):
            self.closed = False
            self.drains = 0

        def write(self, data):
            pass

        async def drain(self):
            self.drains += 1
            raise ConnectionResetError("Connection reset by peer")

        def close(self):
            self.closed = True

        def get_extra_info(self, name):
            return ("198.51.100.7", 40213)

    async def main():
        async with Dispatcher(ServeConfig(max_wait_ms=1.0)) as d:
            writer = DyingWriter()
            reader = FakeReader([{"op": "ping", "id": 1},
                                 {"op": "ping", "id": 2}])
            # must return cleanly — any escaping exception would kill
            # the asyncio.start_server accept task for EVERY client
            await handle_connection(d, reader, writer)
            return writer

    writer = run_async(main())
    assert writer.closed
    assert writer.drains >= 1
    lost = [r for r in obs.snapshot()
            if r.get("kind") == "serve_conn_lost"]
    assert lost and "ConnectionResetError" in lost[0]["payload"]["error"]
    # the second reply attempt short-circuits: one connection loss is
    # recorded once, not once per in-flight reply
    assert len(lost) == 1


# ------------------------------------------------------------ loadgen


def test_loadgen_row_shape_and_accounting():
    from cs87project_msolano2_tpu.serve.loadgen import run_offered_load

    async def main():
        async with Dispatcher(ServeConfig(max_wait_ms=1.0)) as d:
            return await run_offered_load(d, N, rps=40.0,
                                          duration_s=0.2)

    row = run_async(main())
    assert row["requests"] == row["completed"] + row["rejected"] \
        + row["failed"]
    assert row["completed"] > 0 and row["offered_rps"] == 40.0
    assert row["p99_ms"] >= row["p50_ms"] > 0
    assert row["queue_p99_ms"] >= 0 and row["compute_p99_ms"] > 0
    assert row["shape"] == "n2^8:natural"


def test_loadgen_all_rejected_keeps_stable_schema_no_crash():
    """The summary must survive a cell where EVERY arrival was
    rejected (total saturation): same row keys, None latency fields —
    never a percentile() crash on an empty population."""
    from cs87project_msolano2_tpu.serve.loadgen import run_offered_load

    class AlwaysFull:
        async def submit(self, *a, **kw):
            raise QueueFull("full", retry_after_ms=5.0)

    async def main():
        rejected_row = await run_offered_load(AlwaysFull(), N,
                                              rps=50.0,
                                              duration_s=0.1)
        async with Dispatcher(ServeConfig(max_wait_ms=1.0)) as d:
            ok_row = await run_offered_load(d, N, rps=40.0,
                                            duration_s=0.1)
        return rejected_row, ok_row

    rejected_row, ok_row = run_async(main())
    assert rejected_row["completed"] == 0
    assert rejected_row["rejected"] == rejected_row["requests"] > 0
    for key in ("p50_ms", "p99_ms", "queue_p50_ms", "queue_p99_ms",
                "compute_p50_ms", "compute_p99_ms"):
        assert rejected_row[key] is None, key
    assert rejected_row["retry_after_p50_ms"] == 5.0
    # a fully-completed row reports no rejections the same way
    assert ok_row["retry_after_p50_ms"] is None
    # SCHEMA STABILITY: both rows expose exactly the same keys
    assert set(rejected_row) == set(ok_row)


def test_percentile_or_none_contract():
    from cs87project_msolano2_tpu.serve import percentile_or_none

    assert percentile_or_none([], 99) is None
    assert percentile_or_none([3.0, 1.0, 2.0], 50) == 2.0


# ------------------------------------------------------- entry points


def test_serve_smoke_cli_end_to_end(capsys):
    """The `make serve-smoke` gate, in-process: coalescing asserted
    via obs counters, responses verified, zero schema-invalid
    events."""
    from cs87project_msolano2_tpu.serve.cli import serve_main

    rc = serve_main(["--smoke", "-k", "8", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0, out["problems"]
    assert out["ok"] is True
    assert 0 < out["same_shape_batches"] < out["same_shape_requests"]
    assert out["schema_invalid_events"] == 0
    assert out["events"] > 0


def test_bench_serve_load_smoke_emits_slo_rows(capsys):
    """`bench.py --serve-load --smoke` must emit the SLO row set in
    the BENCH round format and exit 0 even when cells saturate."""
    import bench

    rc = bench.main(["--serve-load", "--smoke",
                     "--load-rps", "60", "--load-duration", "0.2"])
    assert rc == 0
    record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert record["metric"] == "serve_slo_p99_ms"
    assert record["unit"] == "ms" and record["smoke"] is True
    rows = record["serve_load"]
    assert rows and all(
        {"offered_rps", "achieved_rps", "requests"} <= set(r)
        for r in rows)
    completed = [r for r in rows if "p99_ms" in r]
    assert completed and record["value"] == max(r["p99_ms"]
                                                for r in completed)


def test_bench_serve_load_chaos_completes_tagged(capsys):
    """Injected serve chaos during the load run: rc stays 0 and the
    record tags degraded (the resilience acceptance)."""
    import bench

    with resilience.inject("serve", "capacity"):
        rc = bench.main(["--serve-load", "--smoke",
                         "--load-rps", "40", "--load-duration", "0.15"])
    assert rc == 0
    record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert record.get("degraded") is True
    assert any(r["degraded"] for r in record["serve_load"])
