"""Self-healing multichip tests (docs/MULTICHIP.md): collective
supervision (heartbeats, strict deadline validation, supervised
abort + cancellation), stall fault injection, the communication-free
escape path's bit-parity and collective-free-HLO contracts, multihost
fallback consensus, the end-to-end chaos recovery loop, and the
sharded harness's journaled kill-safe resume.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs87project_msolano2_tpu import obs
from cs87project_msolano2_tpu.parallel import (
    clear_unhealthy,
    fft2_collective_free_planes,
    fft2_sharded_resilient,
    make_mesh,
    poisson_solve_collective_free,
    poisson_solve_sharded,
    poisson_solve_sharded_resilient,
    report_unhealthy,
)
from cs87project_msolano2_tpu.parallel.escape import (
    _fft2_escape_fn,
    _poisson_escape_fn,
)
from cs87project_msolano2_tpu.parallel.fft2d import fft2_sharded_planes
from cs87project_msolano2_tpu.parallel.multihost import agree_on_fallback
from cs87project_msolano2_tpu.resilience import (
    CancellationToken,
    CollectiveAborted,
    FaultSpec,
    HostDesyncError,
    Journal,
    collective_watchdog,
    inject,
    maybe_fault,
    rendezvous_deadline_s,
    supervise_collective,
)
from cs87project_msolano2_tpu.resilience.watchdog import (
    DEFAULT_RENDEZVOUS_DEADLINE_S,
    abort_waits_default,
)

COLLECTIVE_HLO_OPS = ("all-to-all", "all-reduce", "all-gather",
                      "collective-permute", "reduce-scatter")


@pytest.fixture
def obs_events():
    """In-process obs buffer for event asserts; always disarmed (and
    the metrics registry cleared — the disabled path must stay a
    verified no-op for later tests) after."""
    from cs87project_msolano2_tpu.obs import metrics

    if obs.enabled():
        obs.disable()
    obs.enable()
    yield
    if obs.enabled():
        obs.disable()
    metrics.reset()


# ------------------------------------------- deadline/knob validation


def test_deadline_env_validated_at_arm_time(monkeypatch, capsys):
    for bad in ("soon", "0", "-5", "inf", "nan"):
        monkeypatch.setenv("PIFFT_RENDEZVOUS_DEADLINE_S", bad)
        assert rendezvous_deadline_s() == DEFAULT_RENDEZVOUS_DEADLINE_S
        err = capsys.readouterr().err
        # the diagnostic names the raw value AND the served value
        assert repr(bad) in err and "60" in err
        # strict mode: a malformed knob fails AT ARM TIME, not never
        with pytest.raises(ValueError, match="positive finite"):
            rendezvous_deadline_s(strict=True)
        with pytest.raises(ValueError, match="positive finite"):
            with collective_watchdog("region", strict=True):
                pass  # pragma: no cover — arm raises first
    monkeypatch.setenv("PIFFT_RENDEZVOUS_DEADLINE_S", "2.5")
    assert rendezvous_deadline_s(strict=True) == 2.5


def test_abort_waits_env_validated(monkeypatch, capsys):
    monkeypatch.setenv("PIFFT_COLLECTIVE_ABORT_WAITS", "3")
    assert abort_waits_default() == 3
    monkeypatch.setenv("PIFFT_COLLECTIVE_ABORT_WAITS", "zero")
    assert abort_waits_default() == 2
    assert "PIFFT_COLLECTIVE_ABORT_WAITS" in capsys.readouterr().err


# -------------------------------------------------- stall fault specs


def test_stall_spec_parse_and_fire():
    spec = FaultSpec.parse("collective:stall=0.01:1.0:2")
    assert spec.kind == "stall" and spec.stall_s == 0.01
    assert spec.prob == 1.0 and spec.count == 2
    # default duration without '='
    assert FaultSpec.parse("collective:stall").stall_s > 0
    with pytest.raises(ValueError, match="stall"):
        FaultSpec.parse("collective:stall=abc")
    with pytest.raises(ValueError, match="> 0"):
        FaultSpec.parse("collective:stall=-1")
    # a stall DELAYS, never raises, and respects its firing cap
    with inject("collective", "stall", stall_s=0.01, count=2) as live:
        for _ in range(4):
            maybe_fault("collective")
        assert live.fired == 2


# --------------------------------------------------------- supervisor


def test_supervise_collective_fast_region_is_untouched(obs_events):
    value, report = supervise_collective(lambda: 42, "fast",
                                         deadline_s=5.0)
    assert value == 42
    assert report.fired == 0 and not report.aborted
    assert not report.recovered


def test_supervise_collective_recovers_and_emits(obs_events, capsys):
    with inject("collective", "stall", stall_s=0.3):
        value, report = supervise_collective(
            lambda: "done", "stuck-then-unstuck",
            deadline_s=0.05, abort_waits=50)
    assert value == "done"
    assert report.recovered and report.fired >= 1
    recs = [r for r in obs.snapshot()
            if r.get("kind") == "collective_recovered"]
    assert recs and recs[-1]["payload"]["waits"] == report.fired
    assert recs[-1]["payload"]["deadline_s"] == 0.05
    assert "collective_recovered" in capsys.readouterr().err


def test_supervise_collective_aborts_past_budget(obs_events):
    # the region itself wedges (the blocked-inside-XLA model: a sleep
    # the supervisor cannot interrupt) and outlives the abort budget
    token = CancellationToken()
    with pytest.raises(CollectiveAborted) as exc_info:
        supervise_collective(lambda: time.sleep(0.5) or "late",
                             "wedged", deadline_s=0.05, abort_waits=2,
                             token=token)
    report = exc_info.value.report
    assert report.aborted and report.fired >= 2
    assert token.cancelled()
    kinds = [r["kind"] for r in obs.snapshot()]
    assert "collective_heartbeat" in kinds
    assert "collective_abandoned" in kinds
    # the abandoned worker finishes anyway and records the late
    # completion (the r05 false-positive shape) instead of losing it
    time.sleep(0.8)
    kinds = [r["kind"] for r in obs.snapshot()]
    assert "collective_late_completion" in kinds


def test_supervised_abort_at_safe_point_never_dispatches(obs_events):
    """A stall BEFORE the region (the probe site) cancels at the safe
    point: the region body itself must never run."""
    ran = []
    with inject("collective", "stall", stall_s=0.5):
        with pytest.raises(CollectiveAborted):
            supervise_collective(lambda: ran.append(1), "pre-wedged",
                                 deadline_s=0.05, abort_waits=2)
    time.sleep(0.6)  # let the worker drain past its stall
    assert ran == [], "cancelled region was still dispatched"


def test_cancellation_token_checkpoint_is_a_safe_point():
    token = CancellationToken()
    token.checkpoint("region")  # not cancelled: no-op
    token.cancel("operator said stop")
    with pytest.raises(CollectiveAborted, match="operator said stop"):
        token.checkpoint("region")
    # a cancelled token also stops a NEW supervised dispatch at the
    # built-in safe point (the worker checks before calling the region)
    with pytest.raises(CollectiveAborted):
        supervise_collective(lambda: "unreachable", "cancelled-early",
                             deadline_s=5.0, token=token)


def test_supervised_region_exceptions_propagate():
    with pytest.raises(ZeroDivisionError):
        supervise_collective(lambda: 1 // 0, "raises", deadline_s=5.0)


def test_straggler_note_names_co_armed_regions(capsys):
    from cs87project_msolano2_tpu.resilience.watchdog import (
        active_regions,
    )

    with collective_watchdog("regionA", deadline_s=30.0):
        assert "regionA" in active_regions()
        with collective_watchdog("regionB", deadline_s=0.05):
            time.sleep(0.15)  # regionB overruns while regionA is armed
    err = capsys.readouterr().err
    assert "co-armed regions still waiting: regionA" in err
    assert active_regions() == []


# --------------------------------------------------- fallback consensus


class _FakeClient:
    def __init__(self, fail=False):
        self.kv = {}
        self.barriers = []
        self.fail = fail

    def key_value_set(self, key, value):
        self.kv[key] = value

    def wait_at_barrier(self, barrier_id, timeout_in_ms):
        if self.fail:
            raise TimeoutError(f"barrier {barrier_id} timed out")
        self.barriers.append((barrier_id, timeout_in_ms))


def test_consensus_single_process_trivially_agrees(obs_events):
    epoch = agree_on_fallback("test-label", reason="unit test")
    assert isinstance(epoch, int) and epoch >= 1
    recs = [r for r in obs.snapshot()
            if r.get("kind") == "fallback_consensus"]
    assert recs and recs[-1]["payload"]["agreed"] is True


def test_consensus_multiprocess_uses_kv_and_barrier():
    client = _FakeClient()
    epoch = agree_on_fallback("test-label", reason="stall",
                              deadline_s=1.5, client=client, processes=4)
    assert client.barriers == [(f"pifft-fallback-{epoch}", 1500)]
    (key, value), = client.kv.items()
    assert key == f"pifft/fallback/{epoch}/0"
    assert "test-label" in value


def test_consensus_timeout_is_a_classified_desync(obs_events):
    with pytest.raises(HostDesyncError, match="fallback consensus"):
        agree_on_fallback("test-label", deadline_s=0.1,
                          client=_FakeClient(fail=True), processes=2)
    recs = [r for r in obs.snapshot()
            if r.get("kind") == "fallback_consensus"]
    assert recs and recs[-1]["payload"]["agreed"] is False


# ------------------------------------- escape path: parity + zero HLO


def rand_c64(shape, seed):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape)
            + 1j * rng.standard_normal(shape)).astype(np.complex64)


def test_fft2_escape_parity_bit_for_bit(devices8):
    """The escape matches the all_to_all path BIT FOR BIT on the
    8-device mesh — same per-shard plans on the same values, only the
    data movement re-planned (both under jit: docs/MULTICHIP.md,
    bit-parity note)."""
    mesh = make_mesh(8)
    x = rand_c64((64, 64), seed=0)
    xr = jnp.asarray(np.real(x)); xi = jnp.asarray(np.imag(x))
    for inverse in (False, True):
        a = jax.jit(lambda r, i, inv=inverse: fft2_sharded_planes(
            r, i, mesh, inverse=inv))(xr, xi)
        b = fft2_collective_free_planes(xr, xi, mesh, inverse=inverse)
        assert np.array_equal(np.asarray(a[0]), np.asarray(b[0]))
        assert np.array_equal(np.asarray(a[1]), np.asarray(b[1]))
        if not inverse:
            # and it is CORRECT, not merely self-consistent
            y = np.asarray(b[0]) + 1j * np.asarray(b[1])
            ref = np.fft.fft2(x.astype(np.complex128))
            assert np.max(np.abs(y - ref)) / np.max(np.abs(ref)) < 1e-5


def test_poisson_escape_parity_bit_for_bit(devices8):
    mesh = make_mesh(8)
    rng = np.random.default_rng(3)
    f = rng.standard_normal((16, 16, 8)).astype(np.float32)
    a = jax.jit(lambda v: poisson_solve_sharded(v, mesh))(f)
    b = poisson_solve_collective_free(f, mesh)
    assert np.array_equal(np.asarray(a), np.asarray(b))


def test_escape_hlo_is_collective_free(devices8):
    """The machine-checked form of the escape's whole point: the
    compiled HLO of both escape bodies contains ZERO collective ops
    (the same check the sharded pi-FFT carries)."""
    mesh = make_mesh(8)
    fn2 = _fft2_escape_fn(mesh, "p", False, 64, 64)
    z = jnp.zeros((64, 64), jnp.float32)
    hlo = jax.jit(fn2).lower(z, z).compile().as_text()
    found = [op for op in COLLECTIVE_HLO_OPS if op in hlo]
    assert not found, f"fft2 escape compiled with collectives: {found}"
    fn3 = _poisson_escape_fn(mesh, "p", 16, 16, 8)
    z3 = jnp.zeros((16, 16, 8), jnp.float32)
    hlo = jax.jit(fn3).lower(z3).compile().as_text()
    found = [op for op in COLLECTIVE_HLO_OPS if op in hlo]
    assert not found, f"poisson escape compiled with collectives: {found}"


# ----------------------------------------------- the chaos recovery loop


def test_chaos_stall_abort_escape_end_to_end(devices8, obs_events):
    """The acceptance loop: injected stall -> supervised abort ->
    consensus -> collective_free escape -> bit-identical result, with
    the degrade trail and the obs events all in place (rc=0 is the
    CLI's form of this assert: `pifft multichip smoke`)."""
    mesh = make_mesh(8)
    x = rand_c64((32, 32), seed=1)
    y_ok, rep_ok = fft2_sharded_resilient(x, mesh)
    assert not rep_ok.escaped and not rep_ok.degraded
    with inject("collective", "stall", stall_s=0.6):
        y_esc, rep = fft2_sharded_resilient(x, mesh, deadline_s=0.1,
                                            abort_waits=2)
    assert rep.escaped and rep.degraded
    assert rep.waits >= 2
    assert isinstance(rep.epoch, int)
    assert [t["to"] for t in rep.trail] == ["collective_free"]
    assert rep.trail[0]["from"] == "all_to_all"
    # bit-identical to the healthy supervised run
    assert np.array_equal(np.asarray(y_ok), np.asarray(y_esc))
    # the report round-trips to a JSON-safe record (the harness
    # journals it)
    json.dumps(rep.to_record())
    kinds = {r["kind"] for r in obs.snapshot()}
    for wanted in ("collective_heartbeat", "collective_abandoned",
                   "fallback_consensus", "demotion",
                   "collective_escape_completed"):
        assert wanted in kinds, f"missing {wanted} (have {kinds})"
    problems = [p for r in obs.snapshot()
                for p in obs.validate_event(r)]
    assert problems == []


def test_poisson_chaos_recovery(devices8, obs_events):
    mesh = make_mesh(8)
    rng = np.random.default_rng(5)
    f = rng.standard_normal((16, 16, 8)).astype(np.float32)
    u_ok, rep_ok = poisson_solve_sharded_resilient(f, mesh)
    assert not rep_ok.escaped
    with inject("collective", "stall", stall_s=0.6):
        u_esc, rep = poisson_solve_sharded_resilient(
            f, mesh, deadline_s=0.1, abort_waits=2)
    assert rep.escaped and rep.degraded
    assert np.array_equal(np.asarray(u_ok), np.asarray(u_esc))


def test_unhealthy_device_skips_doomed_dispatch(devices8, obs_events,
                                               monkeypatch):
    """An out-of-band unhealthy report escapes DIRECTLY: the primary
    collective is never dispatched (no 2-deadline wait to pay)."""
    from cs87project_msolano2_tpu.parallel import escape as escape_mod

    def never(*a, **k):  # pragma: no cover — the assert is that
        raise AssertionError("primary was dispatched")

    monkeypatch.setattr(escape_mod, "supervise_collective", never)
    mesh = make_mesh(8)
    report_unhealthy(jax.devices()[0], "operator: ECC errors")
    try:
        x = rand_c64((32, 32), seed=2)
        y, rep = fft2_sharded_resilient(x, mesh)
        assert rep.escaped and rep.waits == 0
        assert rep.trail and rep.trail[0]["to"] == "collective_free"
        assert "unhealthy" in rep.trail[0]["reason"]
        ref = np.fft.fft2(x.astype(np.complex128))
        assert np.max(np.abs(np.asarray(y) - ref)) \
            / np.max(np.abs(ref)) < 1e-5
    finally:
        clear_unhealthy()


def test_escape_is_transport_only_other_faults_propagate(devices8):
    """A non-stall fault inside the primary body belongs to the plan
    degradation chain / retry layer, not to the transport escape."""
    from cs87project_msolano2_tpu.parallel.escape import run_with_escape

    mesh = make_mesh(8)

    def primary():
        raise ZeroDivisionError("not a collective problem")

    with pytest.raises(ZeroDivisionError):
        run_with_escape(primary, lambda: None, "label", mesh,
                        deadline_s=5.0)


# -------------------------------------------------- journal run config


def test_journal_guard_config(tmp_path):
    j = Journal(str(tmp_path / "j.jsonl"))
    j.guard_config({"dataset": "sharded", "full": False})
    # same config: fine (and idempotent)
    j2 = Journal(str(tmp_path / "j.jsonl"))
    j2.guard_config({"dataset": "sharded", "full": False})
    # a journal may carry EXTRA config keys a newer writer added
    j3 = Journal(str(tmp_path / "j.jsonl"))
    j3.guard_config({"dataset": "sharded"})
    with pytest.raises(ValueError, match="different run configuration"):
        Journal(str(tmp_path / "j.jsonl")).guard_config(
            {"dataset": "sharded", "full": True})


# ------------------------------------- sharded sweep: journaled resume


@pytest.fixture(scope="module")
def sharded_harness():
    import importlib

    return importlib.import_module("harness.run_sharded_experiments")


def test_sharded_sweep_resume_recomputes_nothing(sharded_harness,
                                                 tmp_path, monkeypatch):
    """Kill a sharded sweep mid-cell and --resume must recompute no
    completed cell — and preserve the collective cross-check's degrade
    trail instead of re-risking the wedge (acceptance criterion)."""
    mod = sharded_harness
    out = str(tmp_path)
    argv = ["--n-grid", "1024", "--p-grid", "1,2", "-T", "2",
            "--out", out]
    assert mod.main(argv) == 0
    tsv = os.path.join(out, "fourier-parallel-pi-sharded-results.tsv")
    rows = open(tsv).read().splitlines()
    assert len(rows) == 4  # 2 cells x 2 reps
    journal = mod.journal_for(tsv)
    cells = journal.load()
    assert "collective_crosscheck" in cells
    trail_before = cells["collective_crosscheck"]

    # simulate the kill that truncates the TSV's last line mid-write:
    # the fsynced journal still holds the rep, so nothing re-runs
    with open(tsv, "w") as fh:
        fh.write("\n".join(rows[:-1]) + "\n1024\t2\t0.0")

    calls = []
    real_time_ms = mod.time_ms
    monkeypatch.setattr(mod, "time_ms",
                        lambda *a, **k: calls.append(1)
                        or real_time_ms(*a, **k))
    assert mod.main(argv) == 0
    assert calls == [], "resume recomputed completed cells"
    # the degrade trail survived the resume untouched
    cells_after = mod.journal_for(tsv).load()
    assert cells_after["collective_crosscheck"] == trail_before


def test_sharded_sweep_no_resume_starts_fresh(sharded_harness, tmp_path,
                                              monkeypatch):
    """--no-resume is a FRESH dataset: the grid re-runs AND the
    append-only TSV rotates — two runs' timings must never splice into
    one per-cell replication count."""
    mod = sharded_harness
    out = str(tmp_path)
    argv = ["--n-grid", "1024", "--p-grid", "1", "-T", "1", "--out", out]
    assert mod.main(argv) == 0
    tsv = os.path.join(out, "fourier-parallel-pi-sharded-results.tsv")
    calls = []
    real_time_ms = mod.time_ms
    monkeypatch.setattr(mod, "time_ms",
                        lambda *a, **k: calls.append(1)
                        or real_time_ms(*a, **k))
    assert mod.main(argv + ["--no-resume"]) == 0
    assert calls, "--no-resume must re-run the grid"
    rows = [ln for ln in open(tsv).read().splitlines() if ln.strip()]
    assert len(rows) == 1, f"TSV spliced two runs: {rows}"
