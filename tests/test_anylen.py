"""Any-length plan tests (docs/PLANS.md, "Arbitrary n") — all offline
(CPU, tier-1-safe): pad-policy properties, static variant routing,
numpy parity across the Bluestein/Rader/mixedradix matrix (forward +
inverse, c2c + r2c/c2r, batched), the chirp-spectrum cache, the
degrade walk past the pow2-only kernel rungs, schema-v4 key
validation, the cheapest-length bytes property the fftconv gate rides,
exact-n shape labels, and the serve front door at arbitrary n."""

import asyncio

import numpy as np
import pytest

from cs87project_msolano2_tpu import plans
from cs87project_msolano2_tpu.ops import anylen
from cs87project_msolano2_tpu.plans import cache as plan_cache
from cs87project_msolano2_tpu.plans import ladder
from cs87project_msolano2_tpu.plans.core import (
    SCHEMA_VERSION,
    PlanKey,
)

#: split3 forward budget / looser roundtrip budget (two transforms)
TOL = 1e-5
RT_TOL = 1e-4


@pytest.fixture(autouse=True)
def fresh_memory_cache():
    plan_cache.clear(memory=True, disk=False)
    yield
    plan_cache.clear(memory=True, disk=False)


def _rel(got, ref):
    return float(np.max(np.abs(got - ref)) / np.max(np.abs(ref)))


def _planes(rng, n, batch=()):
    return (rng.standard_normal(batch + (n,)).astype(np.float32),
            rng.standard_normal(batch + (n,)).astype(np.float32))


# ------------------------------------------------------ pad policy


def test_pad_candidates_properties():
    for n in (3, 5, 7, 63, 100, 127, 719, 720, 999, 1000, 4097, 8190):
        cands = anylen.pad_candidates(n)
        lo = max(2 * n - 1, 2)
        naive = anylen.next_pow2(lo)
        assert cands == sorted(cands)
        assert 1 <= len(cands) <= 3
        assert naive in cands  # the naive pad is always raced
        for p in cands:
            assert p >= lo  # linear-in-circular feasibility
            assert p <= naive  # never worse than next-pow2
            _, m = anylen.odd_split(p)
            assert m in (1, 3, 5)  # one-level mixedradix subplans
        assert anylen.default_pad(n) == cands[0]


def test_plan_variant_routing():
    assert anylen.plan_variant(127) == "rader"
    assert anylen.plan_variant(8191) == "rader"
    # primes at or below RADER_MIN_N are cheaper as a bare DFT matmul
    assert anylen.plan_variant(7) == "mixedradix"
    assert anylen.plan_variant(720) == "mixedradix"
    assert anylen.plan_variant(1000) == "mixedradix"
    assert anylen.plan_variant(3072) == "mixedradix"
    # odd part 999 = 27*37 > MIXEDRADIX_MAX_ODD and composite
    assert anylen.plan_variant(999) == "bluestein"
    with pytest.raises(ValueError):
        anylen.plan_variant(1024)


def test_primitive_root_generates():
    for p in (7, 127, 8191):
        g = anylen.primitive_root(p)
        seen = {pow(g, q, p) for q in range(p - 1)}
        assert seen == set(range(1, p))


# ------------------------------------------------- parity: the matrix


@pytest.mark.parametrize("n", [2, 7, 127, 720, 999, 3072])
def test_c2c_forward_and_inverse_parity(n):
    rng = np.random.default_rng(n)
    xr, xi = _planes(rng, n)
    p = plans.plan(n, layout="natural")
    yr, yi = p.execute(xr, xi)
    ref = np.fft.fft(xr.astype(np.complex128)
                     + 1j * xi.astype(np.complex128))
    assert _rel(np.asarray(yr) + 1j * np.asarray(yi), ref) <= TOL
    ir, ii = p.execute_inverse(np.asarray(yr), np.asarray(yi))
    assert _rel(np.asarray(ir) + 1j * np.asarray(ii),
                xr + 1j * xi) <= RT_TOL
    if n != 2:
        assert p.variant == anylen.plan_variant(n)
        assert not p.degraded


def test_rader_large_prime_parity():
    n = 8191  # Mersenne prime: the real Rader reach case
    rng = np.random.default_rng(13)
    xr, xi = _planes(rng, n)
    p = plans.plan(n, layout="natural")
    assert p.variant == "rader"
    yr, yi = p.execute(xr, xi)
    ref = np.fft.fft(xr.astype(np.complex128)
                     + 1j * xi.astype(np.complex128))
    assert _rel(np.asarray(yr) + 1j * np.asarray(yi), ref) <= TOL


@pytest.mark.parametrize("n", [7, 720, 999, 1000])
def test_real_domain_parity(n):
    from cs87project_msolano2_tpu.models.real import (
        irfft_planes_fast,
        rfft_planes_fast,
    )

    rng = np.random.default_rng(n)
    x = rng.standard_normal(n).astype(np.float32)
    hr, hi = rfft_planes_fast(x)
    ref = np.fft.rfft(x.astype(np.float64))
    assert hr.shape[-1] == n // 2 + 1
    assert _rel(np.asarray(hr) + 1j * np.asarray(hi), ref) <= TOL
    back = irfft_planes_fast(np.asarray(hr), np.asarray(hi), n=n)
    assert _rel(np.asarray(back), x.astype(np.float64)) <= RT_TOL


def test_batched_any_length():
    n = 1000
    rng = np.random.default_rng(5)
    xr, xi = _planes(rng, n, batch=(3,))
    p = plans.plan_for((3, n), layout="natural")
    yr, yi = p.execute(xr, xi)
    ref = np.fft.fft(xr.astype(np.complex128)
                     + 1j * xi.astype(np.complex128), axis=-1)
    assert _rel(np.asarray(yr) + 1j * np.asarray(yi), ref) <= TOL


def test_chirp_cache_hits():
    from cs87project_msolano2_tpu import obs
    from cs87project_msolano2_tpu.obs import metrics

    anylen.chirp_cache_clear()
    owned = not obs.enabled()
    if owned:
        obs.enable()
    try:
        anylen.bluestein_tables(999, 2048)
        miss = metrics.counter_value("pifft_anylen_chirp_cache_total",
                                     result="miss")
        anylen.bluestein_tables(999, 2048)
        hit = metrics.counter_value("pifft_anylen_chirp_cache_total",
                                    result="hit")
        assert miss >= 1 and hit >= 1
    finally:
        if owned:
            obs.disable()


# --------------------------------------------------- ladder routing


def test_candidates_race_pads():
    key = plans.make_key(999, layout="natural")
    cands = ladder.candidates(key)
    blu = [(v, p) for v, p in cands if v == "bluestein"]
    assert blu, cands
    assert {p["pad"] for _, p in blu} >= set(anylen.pad_candidates(999))
    # every raced candidate for a non-pow2 key is an any-length
    # variant (the precision race re-lists the same variants)
    assert all(v in anylen.ANYLEN_VARIANTS for v, _ in cands), cands


def test_static_default_variants():
    for n, want in ((127, "rader"), (1000, "mixedradix"),
                    (999, "bluestein")):
        key = plans.make_key(n, layout="natural")
        variant, params = ladder.static_default(key)
        assert variant == want
        if want == "rader":
            assert params["pad"] == anylen.default_pad(n - 1)
        if want == "bluestein":
            assert params["pad"] == anylen.default_pad(n)


# ------------------------------------------------ degrade + schema


def test_anylen_degrade_walks_to_jnp():
    from cs87project_msolano2_tpu.resilience.inject import inject

    n = 999
    rng = np.random.default_rng(7)
    xr, xi = _planes(rng, n)
    with inject("anylen", "capacity", prob=1.0):
        p = plans.plan(n, layout="natural")
        yr, yi = p.execute(xr, xi)
    assert p.degraded
    assert p.demotions[-1]["to"] == "jnp-fft"
    # the pow2-only kernel rungs never claim to have served
    assert all("fourstep" not in d["to"] and d["to"] != "rql"
               for d in p.demotions)
    ref = np.fft.fft(xr.astype(np.complex128)
                     + 1j * xi.astype(np.complex128))
    assert _rel(np.asarray(yr) + 1j * np.asarray(yi), ref) <= TOL


def test_any_n_key_token_round_trip():
    key = PlanKey(device_kind="TPU test-kind", n=1000, batch=(3,),
                  layout="natural", precision="split3")
    tok = key.token()
    assert f'"v":{SCHEMA_VERSION}' in tok.replace(" ", "")
    assert PlanKey.from_token(tok) == key


def test_old_schema_token_refused():
    import json

    key = PlanKey(device_kind="TPU test-kind", n=1000, batch=(),
                  layout="natural", precision="split3")
    d = json.loads(key.token())
    d["v"] = SCHEMA_VERSION - 1
    with pytest.raises(ValueError):
        PlanKey.from_token(json.dumps(d))


def test_pi_layout_still_requires_pow2():
    with pytest.raises(ValueError):
        PlanKey(device_kind="cpu", n=1000, batch=(), layout="pi",
                precision="split3")
    # pow2 pi keys are untouched
    PlanKey(device_kind="cpu", n=1024, batch=(), layout="pi",
            precision="split3")


def test_real_domain_any_n_keys():
    for n in (999, 1000):
        PlanKey(device_kind="cpu", n=n, batch=(), layout="natural",
                precision="split3", domain="r2c")
    with pytest.raises(ValueError):
        PlanKey(device_kind="cpu", n=1, batch=(), layout="natural",
                precision="split3", domain="r2c")


# --------------------------------------- cheapest_length + roofline


def test_cheapest_length_properties():
    from cs87project_msolano2_tpu.apps.spectral import (
        _CHEAP_ODD_PARTS,
        cheapest_length,
    )

    for v in (2, 100, 768, 896, 1000, 4097, 100000):
        n = cheapest_length(v)
        assert n >= v
        assert n % 2 == 0
        assert n <= anylen.next_pow2(v)
        _, m = anylen.odd_split(n)
        assert m in _CHEAP_ODD_PARTS
    # identity on powers of two: the committed fusion gate's length
    # (4096) must not move
    for v in (2, 4096, 1 << 20):
        assert cheapest_length(v) == v


def test_spectral_bytes_never_worse_than_pow2():
    from cs87project_msolano2_tpu.apps.spectral import cheapest_length
    from cs87project_msolano2_tpu.utils.roofline import (
        spectral_hbm_bytes,
    )

    for v in (896, 1000, 3 * (1 << 8), 100000):
        cheap = spectral_hbm_bytes("conv", cheapest_length(v))
        pow2 = spectral_hbm_bytes("conv", anylen.next_pow2(v))
        assert cheap <= pow2
    # the non-trivial case strictly wins
    assert spectral_hbm_bytes("conv", cheapest_length(3 * (1 << 8))) \
        < spectral_hbm_bytes("conv", anylen.next_pow2(3 * (1 << 8)))


def test_fft_hbm_bytes_pad_aware():
    from cs87project_msolano2_tpu.utils.roofline import fft_hbm_bytes

    n, pad = 999, 2048
    padded = fft_hbm_bytes(n, 2, pad_n=pad)
    unpadded = fft_hbm_bytes(n, 2)
    assert padded > unpadded  # carries charged at the pad length
    assert fft_hbm_bytes(n, 0, pad_n=pad) == fft_hbm_bytes(n, 0)


def test_fftconv_picks_cheap_length():
    from cs87project_msolano2_tpu.apps.spectral import fftconv

    rng = np.random.default_rng(3)
    a = rng.standard_normal(640).astype(np.float32)
    v = rng.standard_normal(129).astype(np.float32)
    got = np.asarray(fftconv(a, v))  # linear length 768 = 3*2^8
    ref = np.convolve(a.astype(np.float64), v.astype(np.float64))
    assert got.shape[0] == ref.shape[0]
    assert _rel(got, ref) <= TOL


# ------------------------------------------------- labels + loader


def test_shape_label_exact_n():
    from cs87project_msolano2_tpu.serve.loadgen import shape_label

    assert shape_label(1024, "natural") == "n2^10:natural"
    assert shape_label(1000, "natural") == "n1000:natural"
    assert shape_label(1000, "natural", "conv") == "n1000:natural:conv"


def test_loader_parses_exact_n_rows():
    from cs87project_msolano2_tpu.analyze.loader import (
        BenchRound,
        Fingerprint,
        bench_samples,
    )

    rnd = BenchRound(index=1, path="BENCH_r01.json",
                     metrics={"n2^13_ms": 1.0, "n1000_ms": 2.0,
                              "rfft1000_ms": 3.0, "conv_np768_ms": 4.0,
                              "conv_np768_hbm_bytes": 5.0},
                     fingerprint=Fingerprint())
    by = {s.metric: s for s in bench_samples(rnd)}
    assert by["n2^13_ms"].n == 1 << 13
    assert by["n1000_ms"].n == 1000
    assert by["n1000_ms"].domain == "c2c"
    assert by["rfft1000_ms"].n == 1000
    assert by["rfft1000_ms"].domain == "r2c"
    assert by["conv_np768_ms"].n == 768
    assert by["conv_np768_ms"].op == "conv"
    assert by["conv_np768_hbm_bytes"].n == 768


# ------------------------------------------------- serve front door


def test_shape_spec_any_n():
    from cs87project_msolano2_tpu.serve.shapes import (
        MAX_SERVED_N,
        ShapeSpec,
    )

    ShapeSpec(n=1000)
    ShapeSpec(n=999, domain="r2c")
    with pytest.raises(ValueError):
        ShapeSpec(n=1)
    with pytest.raises(ValueError):
        ShapeSpec(n=MAX_SERVED_N + 1)
    with pytest.raises(ValueError):
        ShapeSpec(n=1000, layout="pi")
    ShapeSpec(n=1024, layout="pi")


def test_dispatcher_serves_non_pow2():
    from cs87project_msolano2_tpu.serve import (
        Dispatcher,
        ServeConfig,
        ServeError,
    )

    rng = np.random.default_rng(9)
    n = 1000
    xr, xi = _planes(rng, n)

    async def run():
        cfg = ServeConfig(max_wait_ms=1.0)
        async with Dispatcher(cfg) as d:
            resp = await d.submit(xr, xi)
            bad = None
            try:
                await d.submit(np.zeros(1, np.float32),
                               np.zeros(1, np.float32))
            except ServeError as e:
                bad = e
            return resp, bad

    resp, bad = asyncio.run(run())
    assert resp.plan_variant in anylen.ANYLEN_VARIANTS
    assert not resp.degraded
    ref = np.fft.fft(xr.astype(np.complex128)
                     + 1j * xi.astype(np.complex128))
    assert _rel(np.asarray(resp.yr) + 1j * np.asarray(resp.yi),
                ref) <= TOL
    assert bad is not None  # n=1 is a structured refusal
